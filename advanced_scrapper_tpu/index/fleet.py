"""Replicated, fault-tolerant sharded index fleet — the client half.

:class:`ShardedIndexClient` presents the :class:`~.store.PersistentIndex`
API (``probe_batch`` / ``insert_batch`` / ``check_and_add_batch`` /
``allocate_doc_ids`` / ``log_names`` / ``doc_id_floor``) over a fleet of
:class:`~.remote.IndexShardServer` nodes, so every existing caller — the
engine's ``dedup_against_index``, the TPU batch backend's persist mode —
scales past one disk by changing a config string, not a call site.

**Topology.**  The uint64 band-key space is consistent-hashed (virtual
nodes on a ring) into N shards; each shard is a primary plus a
configurable replica.  All postings for a key live on exactly one shard,
so a probe's global minimum doc id is the minimum over per-shard answers
— the property that keeps fleet attribution byte-equal to a single-node
index.

**Writes** replicate synchronously: a posting batch is acked only when
every live node of its shard applied it (same request id on each — the
transport's idempotency cache and the shard's semantic insert filter make
redelivery harmless).  **Reads** go to the shard's current write target,
min-combined with the local spill overlay.

**Failover.**  A node that misses its deadline is marked down and counted.
If it was the write target, the shard enters *promotion*: reads move to
the surviving replica immediately; writes spill until the candidate has
answered ``health_checks`` consecutive pings, then it is promoted and the
spill journal replays into it.  A shard with NO reachable node degrades
gracefully: writes journal to a local WAL (crash-safe through the fsio
seam, reloaded on client restart) with an in-memory overlay answering
probes for the spilled postings, and the journal replays — original
request ids — when any node returns.  Degraded probes that might miss
history are counted, never raised: the pipeline keeps flowing.

**The live-node invariant.**  Every write a shard ACKS is also recorded
in a *gap ledger* for each node that missed it (dead, or failed the
call); a returning node must absorb its ledger before it rejoins.  So
``live ⇒ holding every acked posting``, and promotion may safely elect
any live node — a replica that was briefly down while the primary took
writes can never be promoted into silent data loss.  A ledger that
outgrows ``GAP_LIMIT_POSTINGS`` is dropped and its node sits out this
client's lifetime (counted; an operator resync is cheaper than
unbounded client RAM).

Every edge is on the telemetry plane: per-shard RPC latency histograms,
retry / failover / promotion / spill / replay counters, and a
``fleet_status()`` dict for ``/status``.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from advanced_scrapper_tpu.index.repair import (
    interval_add,
    interval_sub,
    mix64,
    range_mask,
)
from advanced_scrapper_tpu.index.remote import (
    CANARY_SPACE_PREFIX,
    namespace_policy,
)
from advanced_scrapper_tpu.index.store import NO_DOC, resolve_intra_batch
from advanced_scrapper_tpu.runtime import FanoutPool
from advanced_scrapper_tpu.index.wal import WriteAheadLog, replay_wal
from advanced_scrapper_tpu.net.rpc import (
    RpcClient,
    RpcOverloaded,
    RpcUnavailable,
)

__all__ = [
    "FleetSpec",
    "ShardedIndexClient",
    "open_fleet_index",
    "ring_assign",
]


def open_fleet_index(cfg, index_dir: str, *, space: str = "bands", **kw):
    """THE fleet-client factory — every call site (the TPU batch
    backend's persist mode, ``NearDupEngine.open_stream_index``) builds
    its :class:`ShardedIndexClient` here, so the knob-to-constructor
    mapping and the spill layout can never drift between paths.

    ``cfg`` is anything carrying the ``DedupConfig`` fleet fields
    (``index_fleet`` / ``index_fleet_timeout`` / ``index_fleet_retries``
    / ``index_fleet_health_checks``); ``index_dir`` is the LOCAL
    directory — in fleet mode it holds only the spill journals."""
    return ShardedIndexClient(
        FleetSpec.parse(cfg.index_fleet),
        space=space,
        spill_dir=os.path.join(index_dir, "spill"),
        timeout=cfg.index_fleet_timeout,
        retries=cfg.index_fleet_retries,
        health_checks=cfg.index_fleet_health_checks,
        **kw,
    )

_I64_MAX = np.iinfo(np.int64).max


# -- topology ----------------------------------------------------------------

@dataclass(frozen=True)
class FleetSpec:
    """Parsed fleet topology: ``shards[i]`` is that shard's replica set,
    primary first.  Wire syntax (the ``DedupConfig.index_fleet`` string)::

        host:port|host:port ; host:port|host:port ; ...

    ``;`` separates shards, ``|`` separates a shard's replicas.
    Whitespace is ignored.  One shard, one node is valid (a remote
    single-node index with no failover)."""

    shards: tuple[tuple[tuple[str, int], ...], ...]

    @classmethod
    def parse(cls, spec: str) -> "FleetSpec":
        shards = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            nodes = []
            for ep in part.split("|"):
                ep = ep.strip()
                if not ep:
                    continue
                host, _, port = ep.rpartition(":")
                if not host or not port.isdigit():
                    raise ValueError(
                        f"bad fleet endpoint {ep!r} in {spec!r} "
                        "(want host:port|host:port;host:port|...)"
                    )
                nodes.append((host, int(port)))
            if nodes:
                shards.append(tuple(nodes))
        if not shards:
            raise ValueError(f"fleet spec {spec!r} names no shards")
        return cls(shards=tuple(shards))

    @property
    def num_shards(self) -> int:
        return len(self.shards)


_RING_CACHE: dict[tuple[int, int], tuple[np.ndarray, np.ndarray]] = {}


def _ring(num_shards: int, vnodes: int) -> tuple[np.ndarray, np.ndarray]:
    """Sorted ring points + owning shard per point.  Pure function of
    ``(num_shards, vnodes)`` — every client of a fleet, in every process,
    on every run, maps a key to the same shard."""
    got = _RING_CACHE.get((num_shards, vnodes))
    if got is not None:
        return got
    pts, owner = [], []
    for s in range(num_shards):
        for v in range(vnodes):
            h = hashlib.blake2b(
                f"astpu-fleet|{s}|{v}".encode(), digest_size=8
            ).digest()
            pts.append(int.from_bytes(h, "little"))
            owner.append(s)
    pts = np.asarray(pts, np.uint64)
    owner = np.asarray(owner, np.int32)
    order = np.argsort(pts)
    out = (pts[order], owner[order])
    _RING_CACHE[(num_shards, vnodes)] = out
    return out


#: splitmix64 finalizer mapping band keys to ring positions — ONE
#: definition, shared with the repair/reshard planes (``repair.mix64``),
#: so a migration range computed there selects exactly the keys this
#: router sends to the same arc
_mix64 = mix64


def ring_assign(
    keys: np.ndarray, num_shards: int, vnodes: int = 64
) -> np.ndarray:
    """``int32[n]`` owning shard per uint64 key (consistent-hash ring:
    first ring point clockwise of the mixed key, wrapping)."""
    if num_shards == 1:
        return np.zeros(keys.shape, np.int32)
    pts, owner = _ring(num_shards, vnodes)
    ix = np.searchsorted(pts, _mix64(np.asarray(keys, np.uint64)))
    return owner[ix % len(pts)]


# -- per-shard state ---------------------------------------------------------

@dataclass
class _Node:
    address: tuple[str, int]
    client: RpcClient
    alive: bool = True


@dataclass
class _Shard:
    sid: int
    nodes: list[_Node]
    write_target: int = 0          # index into nodes
    promoting: bool = False        # write target lost, candidate unproven
    replaying: bool = False        # a spill replay is on this thread's stack
    last_revive: float = 0.0       # monotonic stamp of the last dead-node ping
    pending: list = field(default_factory=list)  # [(request_id, keys, docs)]
    overlay: dict = field(default_factory=dict)  # key → min doc (spilled)
    gaps: dict = field(default_factory=dict)     # node ix → [(rid, keys, docs)]
    #   writes ACKED by the shard while this node was unreachable — the
    #   backfill a returning node must absorb BEFORE it may rejoin (else a
    #   later promotion could elect a replica missing acked postings)
    gap_overflow: set = field(default_factory=set)  # node ix: gap ledger
    #   dropped past the cap — the node may only return through a FULL
    #   digest-verified resync (_resync_node), never the plain drain path
    resyncing: set = field(default_factory=set)  # node ix: a resync is in
    #   flight — a second caller must not re-arm (and thereby wipe) the
    #   first's gap ledger
    journal: WriteAheadLog | None = None
    lock: threading.RLock = field(default_factory=threading.RLock)

    def live_nodes(self) -> list[_Node]:
        return [n for n in self.nodes if n.alive]


class ShardedIndexClient:
    """Fleet-backed drop-in for :class:`~.store.PersistentIndex`."""

    _seq_lock = threading.Lock()
    _seq = 0

    def __init__(
        self,
        spec: FleetSpec | str,
        *,
        space: str = "bands",
        spill_dir: str | None = None,
        timeout: float = 5.0,
        retries: int = 2,
        health_checks: int = 2,
        health_timeout: float = 0.5,
        vnodes: int = 64,
        connect=None,
        seed: int = 0,
        fs=None,
        overload_backoff_cap: float = 2.0,
        overload_budget: float = 45.0,
        sleep=time.sleep,
        gap_limit_postings: int | None = None,
        repair_interval: float | None = None,
        resync_rounds: int = 4,
        digest_bits: int | None = None,
    ):
        """``spill_dir`` holds one journal per shard (``shardN-<space>
        .spill``); ``None`` disables the durable journal (spills are then
        memory-only — fine for tests, wrong for production).  ``connect``
        is the chaos seam: a dialer wrapped under every node connection.
        """
        self.spec = spec if isinstance(spec, FleetSpec) else FleetSpec.parse(spec)
        self.space = space
        self.spill_dir = spill_dir
        self.timeout = timeout
        self.health_checks = health_checks
        self.health_timeout = health_timeout
        self.vnodes = vnodes
        #: overload discipline: an RpcOverloaded answer (or a deadline
        #: miss while the node still answers pings) backs off IN PLACE —
        #: capped per wait, bounded per call by ``overload_budget``
        #: seconds — and never counts toward failover/promotion
        self.overload_backoff_cap = float(overload_backoff_cap)
        self.overload_budget = float(overload_budget)
        self._sleep = sleep
        #: per-node gap-ledger cap (instance-scoped so tests can shrink
        #: it; defaults to the class constant)
        self.gap_limit_postings = int(
            self.GAP_LIMIT_POSTINGS
            if gap_limit_postings is None else gap_limit_postings
        )
        #: anti-entropy knobs: digest resolution, resync convergence
        #: rounds, and the background repair cadence (seconds; 0 = off,
        #: env ASTPU_FLEET_REPAIR_INTERVAL is the deployment default)
        from advanced_scrapper_tpu.index.repair import DEFAULT_BITS

        self.digest_bits = int(DEFAULT_BITS if digest_bits is None else digest_bits)
        self.resync_rounds = int(resync_rounds)
        if repair_interval is None:
            repair_interval = float(
                os.environ.get("ASTPU_FLEET_REPAIR_INTERVAL", "0") or 0
            )
        self.repair_interval = float(repair_interval)
        self._repair_stop = threading.Event()
        self._repair_thread: threading.Thread | None = None
        from advanced_scrapper_tpu.storage.fsio import default_fs

        self._fs = fs or default_fs()
        # request-id namespace unique ACROSS client processes: a server
        # that outlived a previous client must never replay that client's
        # cached response for this one's fresh request
        self._token = os.urandom(4).hex()
        self._floor = 0           # local doc-id high water (allocator cache)
        self._floor_known = False  # True once a durable floor was synced
        #   from the allocator shard — the gate on degraded local
        #   allocation (see allocate_doc_ids)
        self._postings_written = 0  # client-side view for cheap gauges
        self._floor_lock = threading.Lock()
        self._closed = False
        # node-client construction knobs, kept so a scale-out reshard can
        # grow the topology with clients built exactly like __init__'s
        self._retries = int(retries)
        self._connect = connect
        self._seed = int(seed)
        self._shards: list[_Shard] = []
        for sid, nodes in enumerate(self.spec.shards):
            self._shards.append(
                _Shard(
                    sid=sid,
                    nodes=[
                        _Node(
                            address=addr,
                            client=RpcClient(
                                addr,
                                timeout=timeout,
                                retries=retries,
                                connect=connect,
                                seed=seed * 1000 + sid * 10 + k,
                                # the fleet owns the backoff budget: the
                                # client's INTERNAL retry-after honoring
                                # must sleep in fleet-cap units, or one
                                # call() could overshoot _node_call's
                                # deadline by retries × its own 5 s cap
                                overload_wait_cap=self.overload_backoff_cap,
                            ),
                        )
                        for k, addr in enumerate(nodes)
                    ],
                )
            )
        # per-shard RPC fan-out rides the runtime's Edge-fed pool: remote
        # hops get the same queue telemetry/snapshot as local stages
        self._pool = FanoutPool(
            min(16, 2 * len(self._shards)), name=f"fleet-{space}"
        )
        # -- elastic reshard state (reshard_to) -----------------------------
        self._reshard: dict | None = None      # live cutover: table/ledger/…
        self._reshard_lock = threading.RLock()  # single-flight reshard driver
        self._route_shards = len(self._shards)  # ring size OUTSIDE a reshard
        #: arcs each shard handed off / re-acquired — re-asserted on nodes
        #: that were unreachable when the cutover told them (rejoin sync)
        self._retired: dict[int, list[tuple[int, int]]] = {}
        self._unretired: dict[int, list[tuple[int, int]]] = {}
        self._reshard_dirty: set[int] = set()  # shards owed a control resync
        #: the flip gate: writes intersecting the arc under digest-verify
        #: hold at the door (bounded — see _gate_wait) so the src/dst
        #: comparison sees a settled range
        self._gate_cv = threading.Condition()
        self._gate: tuple[int, int] | None = None
        self._inflight = 0
        self._instrument()
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
            self._reload_spill()
            for sh in self._shards:
                if sh.pending:  # best-effort recovery replay at open
                    self._ensure_write_target(sh)
            self._resume_reshard()
        if self.repair_interval > 0:
            self.start_repair(self.repair_interval)

    # -- telemetry ---------------------------------------------------------

    def _instrument(self) -> None:
        from advanced_scrapper_tpu.obs import telemetry

        with ShardedIndexClient._seq_lock:
            fid = f"{ShardedIndexClient._seq}:{self.space}"
            ShardedIndexClient._seq += 1
        self._fid = fid
        self._m_rpc_s = {}
        for sid in range(len(self._shards)):
            for method in ("probe", "insert"):
                self._m_rpc_s[(sid, method)] = telemetry.histogram(
                    "astpu_fleet_rpc_seconds",
                    "per-shard RPC wall clock, by method",
                    fleet=fid, shard=str(sid), method=method,
                )
        mk = lambda name, help: telemetry.counter(name, help, fleet=fid)  # noqa: E731
        self._m_failovers = mk(
            "astpu_fleet_failovers_total",
            "node deadline/connection failures that re-routed traffic",
        )
        self._m_promotions = mk(
            "astpu_fleet_promotions_total",
            "replicas promoted to write target after health checks",
        )
        self._m_spilled = mk(
            "astpu_fleet_spilled_postings_total",
            "postings journaled locally because no shard node could ack",
        )
        self._m_replayed = mk(
            "astpu_fleet_replayed_postings_total",
            "spilled postings successfully replayed into a recovered shard",
        )
        self._m_degraded = mk(
            "astpu_fleet_degraded_probes_total",
            "probe sub-queries answered without any live shard node "
            "(overlay-only: historical postings on that shard were invisible)",
        )
        self._m_rejoins = mk(
            "astpu_fleet_rejoins_total",
            "dead nodes that absorbed their gap ledger and came back",
        )
        self._m_backfilled = mk(
            "astpu_fleet_backfilled_postings_total",
            "acked-elsewhere postings delivered to returning nodes before "
            "their rejoin",
        )
        # always-on like the overload pair: resync/repair are exactly what
        # an operator audits after an incident, telemetry gate or not
        self._m_resyncs = telemetry.REGISTRY.counter(
            "astpu_fleet_resync_total",
            "gap-overflowed nodes restored by digest-verified full resync "
            "(the auto path behind astpu_fleet_gap_overflow_total)",
            always=True, fleet=fid,
        )
        self._m_resync_postings = telemetry.REGISTRY.counter(
            "astpu_fleet_resync_postings_total",
            "semantic postings streamed into returning nodes during resync",
            always=True, fleet=fid,
        )
        self._m_repair_rounds = telemetry.REGISTRY.counter(
            "astpu_repair_rounds_total",
            "anti-entropy repair passes over the fleet",
            always=True, fleet=fid,
        )
        self._m_repair_ranges = telemetry.REGISTRY.counter(
            "astpu_repair_ranges_total",
            "divergent digest buckets streamed during repair",
            always=True, fleet=fid,
        )
        self._m_repair_postings = telemetry.REGISTRY.counter(
            "astpu_repair_postings_total",
            "postings pushed between replicas to heal divergence",
            always=True, fleet=fid,
        )
        # always-on (not gated by ASTPU_TELEMETRY): the overload-vs-dead
        # distinction is exactly what an operator audits in an incident
        self._m_overload = telemetry.REGISTRY.counter(
            "astpu_fleet_overload_backoff_total",
            "node calls answered RpcOverloaded and backed off in place "
            "(never a failover)",
            always=True, fleet=fid,
        )
        self._m_slow = telemetry.REGISTRY.counter(
            "astpu_fleet_slow_node_backoff_total",
            "calls that missed their deadline while the node still "
            "answered pings — treated as overload, not death",
            always=True, fleet=fid,
        )
        telemetry.gauge_fn(
            "astpu_fleet_gap_postings",
            lambda s: sum(
                int(k.size)
                for sh in s._shards
                for gap in sh.gaps.values()
                for (_r, k, _d) in gap
            ),
            owner=self, fleet=fid,
            help="acked postings awaiting backfill into unreachable nodes",
        )
        telemetry.gauge_fn(
            "astpu_fleet_shards_healthy",
            lambda s: sum(
                1 for sh in s._shards if sh.live_nodes() and not sh.promoting
            ),
            owner=self, fleet=fid,
            help="shards with a proven write target",
        )
        telemetry.gauge_fn(
            "astpu_fleet_spill_pending_postings",
            lambda s: sum(
                int(k.size) for sh in s._shards for (_r, k, _d) in sh.pending
            ),
            owner=self, fleet=fid,
            help="spilled postings awaiting replay",
        )

    def fleet_status(self) -> dict:
        """JSON-able fleet view for ``/status`` dashboards."""
        shards = []
        for sh in self._shards:
            with sh.lock:
                shards.append(
                    {
                        "shard": sh.sid,
                        "nodes": [
                            {
                                "address": f"{n.address[0]}:{n.address[1]}",
                                "alive": n.alive,
                                "write_target": i == sh.write_target,
                            }
                            for i, n in enumerate(sh.nodes)
                        ],
                        "promoting": sh.promoting,
                        "spill_pending": sum(int(k.size) for _r, k, _d in sh.pending),
                        "awaiting_resync": sorted(sh.gap_overflow),
                    }
                )
        out = {"space": self.space, "shards": shards}
        rs = self._reshard
        if rs is not None:
            out["reshard"] = {
                "old_shards": rs["old_n"],
                "new_shards": rs["new_n"],
                "ranges": rs["table"].counts(),
            }
        return out

    # -- spill journal -----------------------------------------------------

    def _journal_path(self, sh: _Shard) -> str:
        return os.path.join(
            self.spill_dir, f"shard{sh.sid}-{self.space}.spill"
        )

    #: replay/reload chunk size — 256k postings ≈ 4 MiB per insert frame,
    #: far under the RPC frame cap (one giant reloaded journal must never
    #: build a frame the server is REQUIRED to refuse)
    REPLAY_CHUNK_POSTINGS = 1 << 18

    def _reload_spill(self) -> None:
        """Client restart: re-arm pending replay from the on-disk journals
        (the 'replayed on recovery' half of the degraded-mode contract),
        chunked so no single replay frame can exceed the RPC cap.

        A torn tail (client SIGKILLed mid spill append) is truncated away
        BEFORE any reopen — the WAL reopen contract (``replay_wal``):
        appending in ``ab`` mode behind torn garbage would make every
        later spilled posting unreplayable forever."""
        for sh in self._shards:
            path = self._journal_path(sh)
            keys, docs, end = replay_wal(path, fs=self._fs)
            if self._fs.exists(path) and self._fs.size(path) > end:
                try:
                    with self._fs.open(path, "r+b") as fh:
                        fh.truncate(end)
                except OSError:
                    pass
            if keys.size:
                for ci, lo in enumerate(
                    range(0, keys.size, self.REPLAY_CHUNK_POSTINGS)
                ):
                    hi = lo + self.REPLAY_CHUNK_POSTINGS
                    rid = (
                        f"spill-{self._token}-{self._fid}-s{sh.sid}"
                        f"-reload{ci}"
                    )
                    sh.pending.append((rid, keys[lo:hi], docs[lo:hi]))
                for k, d in zip(keys.tolist(), docs.tolist()):
                    prev = sh.overlay.get(k)
                    if prev is None or d < prev:
                        sh.overlay[k] = d
                sh.journal = WriteAheadLog(path, fs=self._fs)

    def _spill(self, sh: _Shard, keys: np.ndarray, docs: np.ndarray, rid: str):
        """No node could ack: journal + overlay, never raise."""
        with sh.lock:
            if self.spill_dir is not None:
                try:
                    if sh.journal is None:
                        sh.journal = WriteAheadLog(
                            self._journal_path(sh), fs=self._fs
                        )
                    sh.journal.append(keys, docs)
                    sh.journal.sync()
                except OSError:
                    pass  # overlay still covers this process's lifetime
            sh.pending.append((rid, keys, docs))
            for k, d in zip(keys.tolist(), docs.tolist()):
                prev = sh.overlay.get(k)
                if prev is None or d < prev:
                    sh.overlay[k] = d
        self._m_spilled.inc(int(keys.size))
        from advanced_scrapper_tpu.obs import trace

        trace.record(
            "event", "fleet.spill", shard=sh.sid, postings=int(keys.size)
        )

    def _drop_journal(self, sh: _Shard) -> None:
        if sh.journal is not None:
            sh.journal.close()
            sh.journal = None
        if self.spill_dir is not None:
            try:
                self._fs.remove(self._journal_path(sh))
            except OSError:
                pass

    # -- node health / promotion ------------------------------------------

    def _note_failure(self, sh: _Shard, node: _Node) -> None:
        with sh.lock:
            if not node.alive:
                return
            node.alive = False
            if sh.nodes[sh.write_target] is node:
                sh.promoting = True
        self._m_failovers.inc()
        from advanced_scrapper_tpu.obs import trace

        trace.record(
            "event", "fleet.failover", shard=sh.sid,
            node=f"{node.address[0]}:{node.address[1]}",
        )

    def _try_revive(self, sh: _Shard, *, allow_resync: bool = False) -> None:
        """Ping dead nodes (cheap timeout, rate-limited so a dark shard
        costs one ping round per interval, not per operation); a
        responder must first absorb its gap ledger — every write the
        shard ACKED while it was away — and only then rejoins, as a
        replica, NOT as write target.  That invariant is what makes any
        live node a safe promotion candidate: live ⇒ not missing any
        acked posting.

        ``allow_resync`` gates the expensive leg: gap-OVERFLOWED nodes
        (dropped ledger) can only return through a full digest-verified
        resync, which streams state and must never run inline from the
        probe/insert hot path — only ``checkpoint()``, ``repair_once()``
        and the background repair loop pass True."""
        now = time.monotonic()
        with sh.lock:
            if now - sh.last_revive < self.health_timeout and not allow_resync:
                return
            sh.last_revive = now
        for ix, node in enumerate(sh.nodes):
            if node.alive or (ix in sh.gap_overflow and not allow_resync):
                continue
            if not node.client.ping(timeout=self.health_timeout):
                continue
            if ix in sh.gap_overflow and not self._resync_node(sh, ix, node):
                continue  # still diverged; the next repair round retries
            with sh.lock:
                gap = list(sh.gaps.get(ix, ()))
            backfilled = 0
            n_done = 0
            for rid, keys, docs in gap:
                try:
                    # ONE call-timeout of backoff, not the 45 s default:
                    # _try_revive runs inline from the probe/insert hot
                    # path — a returning-but-overloaded node must cost a
                    # bounded beat, with the next revive round (not this
                    # caller) finishing the backfill
                    self._node_call(
                        sh, node, "insert", {"space": self.space},
                        [keys, docs],
                        request_id=f"{rid}@{node.address[0]}:{node.address[1]}",
                        budget=self.timeout,
                    )
                    n_done += 1
                    backfilled += int(keys.size)
                except (RpcUnavailable, RpcOverloaded):
                    break  # node stays out this round
            with sh.lock:
                # appends-only discipline (like _replay): drop exactly the
                # prefix we delivered; anything appended meanwhile — or
                # left by a mid-drain failure — keeps the node out until
                # the next revive round finishes the job.  Re-check the
                # overflow set AT COMMIT: a ledger that overflowed while
                # we drained was dropped with writes we never delivered —
                # that node must stay out, not rejoin half-backfilled.
                if ix in sh.gap_overflow:
                    continue
                remaining = sh.gaps.get(ix, [])[n_done:]
                if remaining:
                    sh.gaps[ix] = remaining
                else:
                    sh.gaps.pop(ix, None)
                    node.alive = True
            if backfilled:
                self._m_backfilled.inc(backfilled)
            if node.alive:
                self._m_rejoins.inc()
                # a rejoiner may have missed reshard control calls
                # (retire/unretire/fence) while dark — re-assert them
                if (
                    self._retired.get(sh.sid)
                    or self._unretired.get(sh.sid)
                    or sh.sid in self._reshard_dirty
                ):
                    self._sync_reshard_node(sh, node)

    def _ensure_write_target(self, sh: _Shard) -> _Node | None:
        """Advance the shard state machine; returns the proven write
        target or ``None`` (shard fully down → caller spills).

        Promotion is the health-checked path: a candidate replica must
        answer ``health_checks`` consecutive pings before any write
        lands on it, then the spill journal replays into it FIRST — so
        the moment a promoted node serves reads it already holds every
        posting this client ever acked or spilled for the shard."""
        with sh.lock:
            target = sh.nodes[sh.write_target]
            healthy = target.alive and not sh.promoting
        if healthy:
            if sh.pending:
                self._replay(sh)
            return target if target.alive else None
        # write target is down: look for a promotion candidate
        self._try_revive(sh)
        live = sh.live_nodes()
        if not live:
            return None
        candidate = live[0]
        for _ in range(self.health_checks):
            if not candidate.client.ping(timeout=self.health_timeout):
                self._note_failure(sh, candidate)
                return None
        promoted = False
        with sh.lock:
            # a racing thread may have promoted meanwhile — commit once
            target = sh.nodes[sh.write_target]
            if (target.alive and not sh.promoting) or not candidate.alive:
                candidate = target if target.alive else candidate
            else:
                sh.write_target = sh.nodes.index(candidate)
                sh.promoting = False
                promoted = True
        if promoted:
            self._m_promotions.inc()
            from advanced_scrapper_tpu.obs import trace

            trace.record(
                "event", "fleet.promotion", shard=sh.sid,
                node=f"{candidate.address[0]}:{candidate.address[1]}",
            )
        if sh.pending:
            self._replay(sh)
        return candidate if candidate.alive else None

    def _replay(self, sh: _Shard) -> None:
        """Push the spill journal into the (recovered/promoted) shard
        under the ORIGINAL request ids.

        Runs WITHOUT ``sh.lock`` held across the RPCs — a replay of a few
        batches at the full call timeout must not stall every probe and
        status read on the shard.  The ``replaying`` flag makes this a
        single-flight section (and stops ``_ensure_write_target`` from
        re-entering it from inside the replay's own inserts); the commit
        merges in any batches ``_spill`` appended while we were out."""
        with sh.lock:
            if sh.replaying or not sh.pending:
                return
            sh.replaying = True
            batch = list(sh.pending)
        done = 0
        try:
            still: list = []
            for rid, keys, docs in batch:
                if self._replicated_insert(sh, keys, docs, rid, allow_spill=False):
                    done += int(keys.size)
                else:
                    still.append((rid, keys, docs))
            with sh.lock:
                # appends-only discipline: _spill appends, only THIS
                # single-flight section removes — the snapshot's suffix
                # is exactly what arrived while we replayed
                sh.pending = still + sh.pending[len(batch):]
                if not sh.pending:
                    sh.overlay.clear()
                    self._drop_journal(sh)
        finally:
            with sh.lock:
                sh.replaying = False
        if done:
            self._m_replayed.inc(done)
            from advanced_scrapper_tpu.obs import trace

            trace.record("event", "fleet.replay", shard=sh.sid, postings=done)

    # -- anti-entropy: digests, repair, resync ----------------------------

    def _node_digest(self, sh: _Shard, node: _Node):
        _h, (dig, cnt) = self._node_call(
            sh, node, "digest",
            {"space": self.space, "bits": self.digest_bits},
            budget=self.timeout,
        )
        return np.asarray(dig, np.uint64), np.asarray(cnt, np.uint64)

    def _fetch_semantic_range(self, sh: _Shard, node: _Node, lo: int, hi: int):
        """Paged ``fetch_range`` under the frame cap — the shared
        pagination loop, over this client's failure-accounted call."""
        from advanced_scrapper_tpu.index.remote import paged_fetch_range

        return paged_fetch_range(
            lambda header: self._node_call(
                sh, node, "fetch_range",
                {"space": self.space, **header},
                budget=self.timeout,
            ),
            lo, hi, page=self.REPLAY_CHUNK_POSTINGS,
        )

    def _push_pairs(self, sh: _Shard, dst: _Node, keys, docs) -> None:
        rid = (
            f"repair-{self._token}-{self._fid}-s{sh.sid}"
            f"-{self._next_wid()}"
        )
        self._node_call(
            sh, dst, "insert", {"space": self.space}, [keys, docs],
            request_id=f"{rid}@{dst.address[0]}:{dst.address[1]}",
            budget=self.timeout,
        )

    def _heal_pair(
        self, sh: _Shard, a: _Node, b: _Node
    ) -> tuple[int, bool, bool]:
        """One SYMMETRIC anti-entropy pass between two replicas: diff
        bucket digests, stream only the divergent key ranges, and push
        each side the pairs the other is missing (or holds with a LATER
        doc — min-doc semantics).  Postings are inserts, never deletes,
        so a pair present on EITHER side is legitimate acked data and
        propagates both ways — without this, a replica holding a pair no
        peer has (an applied insert whose ack was lost) could never
        digest-match and a resync would spin forever.

        Returns ``(postings_pushed, a_matched, b_matched)``.  Each match
        compares that side's FINAL digest against the expected UNION of
        the two START states (computed locally per divergent bucket, so
        it is immune to writes the pass races): a True for side X proves
        X now covers everything EITHER side held when the pass looked —
        the resync-gate property for a returning node, whose concurrent
        writes sit in the armed gap ledger, not in this check.  A side
        taking live writes mid-pass legitimately reports False and the
        next pass picks up the remainder."""
        dig_a, cnt_a = self._node_digest(sh, a)
        dig_b, cnt_b = self._node_digest(sh, b)
        diff = np.flatnonzero((dig_a != dig_b) | (cnt_a != cnt_b))
        if diff.size == 0:
            return 0, True, True
        from advanced_scrapper_tpu.index.repair import (
            bucket_digests,
            bucket_range,
        )

        # expected end state per bucket: non-divergent buckets already
        # agree (dig_a rows are the shared truth); divergent ones get the
        # locally-computed union digest below
        expect_dig, expect_cnt = dig_a.copy(), cnt_a.copy()
        pushed = 0
        for bucket in diff.tolist():
            lo, hi = bucket_range(bucket, self.digest_bits)
            ka, da = self._fetch_semantic_range(sh, a, lo, hi)
            kb, db = self._fetch_semantic_range(sh, b, lo, hi)
            have_a = dict(zip(ka.tolist(), da.tolist()))
            have_b = dict(zip(kb.tolist(), db.tolist()))
            self._m_repair_ranges.inc()
            merged = dict(have_b)
            for k, d in have_a.items():
                if merged.get(k, _I64_MAX) > d:
                    merged[k] = d
            for dst, src_k, src_d, have in (
                (b, ka, da, have_b),
                (a, kb, db, have_a),
            ):
                need = [
                    j
                    for j, (k, d) in enumerate(
                        zip(src_k.tolist(), src_d.tolist())
                    )
                    if have.get(k, _I64_MAX) > d
                ]
                if need:
                    self._push_pairs(sh, dst, src_k[need], src_d[need])
                    pushed += len(need)
            uk = np.fromiter(merged.keys(), np.uint64, len(merged))
            ud = np.fromiter(merged.values(), np.uint64, len(merged))
            u_dig, u_cnt = bucket_digests(uk, ud, self.digest_bits)
            expect_dig[bucket] = u_dig[bucket]
            expect_cnt[bucket] = u_cnt[bucket]
        self._m_repair_postings.inc(pushed)
        dig_a2, cnt_a2 = self._node_digest(sh, a)
        dig_b2, cnt_b2 = self._node_digest(sh, b)
        a_matched = bool(
            (dig_a2 == expect_dig).all() and (cnt_a2 == expect_cnt).all()
        )
        b_matched = bool(
            (dig_b2 == expect_dig).all() and (cnt_b2 == expect_cnt).all()
        )
        return pushed, a_matched, b_matched

    RESYNC_ROUNDS = 4  # class default; instance knob is resync_rounds

    def _resync_node(self, sh: _Shard, ix: int, node: _Node) -> bool:
        """Full resync of a gap-OVERFLOWED node — the headline healing
        path: its dropped ledger means an unknown set of acked writes is
        missing, so the plain drain can never certify it.  Instead:

        1. arm a FRESH gap ledger (writes acked from this instant on are
           preserved again) — the overflow mark STAYS SET the whole time,
           so a racing plain ``_try_revive`` keeps refusing the node (a
           cleared mark mid-stream would let it rejoin uncertified);
        2. stream the full divergence against a healthy live peer — which
           by the live-node invariant holds every acked posting — via the
           bucket-digest diff, repeating up to ``resync_rounds`` times;
        3. only when the node's digest MATCHES the peer's (and the armed
           ledger survived — an overflowed ledger means unpreserved
           writes) does the mark clear and the node proceed to the
           normal ledger-drain + rejoin gate in ``_try_revive``.

        Digest-matched means the node covers everything acked up to the
        match instant; the armed ledger covers everything after.  On ANY
        failure — no live peer, RPC fault, churn outran the rounds, or an
        unexpected exception (the ``finally`` voids the attempt before it
        propagates) — the mark is still set and the next repair round
        starts over: the node stays out, but never forever."""
        source = None
        with sh.lock:
            for cand in sh.nodes:
                if cand.alive and cand is not node:
                    source = cand
                    break
        if source is None:
            # no healthy peer holds the acked history right now; resync
            # would certify against nothing.  Keep the node out — a peer
            # that rejoins (it holds every acked posting) unblocks this.
            return False
        with sh.lock:
            if ix in sh.resyncing:
                # another thread (checkpoint vs the background repair
                # loop) is mid-resync: re-arming here would WIPE its
                # armed ledger and certify a node missing those writes
                return False
            sh.resyncing.add(ix)
            sh.gaps[ix] = []  # armed: concurrent acked writes land here
        from advanced_scrapper_tpu.obs import trace

        pushed_total = 0
        ok = False
        try:
            for _ in range(max(1, self.resync_rounds)):
                # the gate is the NODE's side only: the live source keeps
                # taking writes mid-pass and legitimately trails the
                # union; the returning node receives nothing but our
                # pushes, so its match is churn-immune
                pushed, _src_ok, matched = self._heal_pair(sh, source, node)
                pushed_total += pushed
                self._m_resync_postings.inc(pushed)
                if matched:
                    with sh.lock:
                        # the armed ledger must have SURVIVED: if it
                        # overflowed mid-resync, writes went unpreserved
                        # and the match certifies a stale state
                        if sh.gaps.get(ix) is not None:
                            sh.gap_overflow.discard(ix)
                            ok = True
                    break
        except (RpcUnavailable, RpcOverloaded):
            pass
        finally:
            with sh.lock:
                sh.resyncing.discard(ix)
                if not ok:
                    # void the attempt: keep the node out (mark stays /
                    # returns set, armed ledger dropped — the next full
                    # push covers it) even when an unexpected exception
                    # is propagating
                    sh.gap_overflow.add(ix)
                    sh.gaps.pop(ix, None)
        if ok:
            self._m_resyncs.inc()
            trace.record(
                "event", "fleet.resync", shard=sh.sid,
                node=f"{node.address[0]}:{node.address[1]}",
                postings=pushed_total,
            )
        return ok

    def repair_once(self) -> dict:
        """One anti-entropy pass over every shard: revive/resync
        returning nodes, then one symmetric heal per live replica pair
        (bucket-digest diff → divergent ranges only, pushed both ways).
        Safe under concurrent inserts — pushes are semantically
        idempotent and the min-doc merge is monotone; a pass that raced
        a write simply leaves the remainder to the next pass.  Returns a
        stats dict."""
        stats = {"shards": 0, "pushed": 0, "pairs": 0, "unmatched": 0}
        self._m_repair_rounds.inc()
        for sh in self._shards:
            self._try_revive(sh, allow_resync=True)
            if sh.sid in self._reshard_dirty:
                # shards owed reshard control calls (retire/unretire/
                # fence marks that failed in line) heal at repair cadence
                self._reshard_dirty.discard(sh.sid)
                for node in sh.live_nodes():
                    self._sync_reshard_node(sh, node)
            live = sh.live_nodes()
            stats["shards"] += 1
            if len(live) < 2:
                continue
            ref = live[0]
            for other in live[1:]:
                try:
                    pushed, m_ref, m_other = self._heal_pair(sh, ref, other)
                except (RpcUnavailable, RpcOverloaded):
                    stats["unmatched"] += 1
                    continue
                stats["pushed"] += pushed
                stats["pairs"] += 1
                if not (m_ref and m_other):
                    stats["unmatched"] += 1
        return stats

    def start_repair(self, interval: float) -> None:
        """Arm the background repair loop (idempotent): every
        ``interval`` seconds one ``repair_once`` pass runs on a daemon
        thread.  ``ASTPU_FLEET_REPAIR_INTERVAL`` (seconds, 0=off) arms it
        at construction; ``interval <= 0`` means OFF here too (never a
        busy loop — ``Event.wait(0)`` returns immediately)."""
        if interval <= 0:
            return
        if self._repair_thread is not None and self._repair_thread.is_alive():
            return
        self.repair_interval = float(interval)
        self._repair_stop.clear()

        def loop():
            while not self._repair_stop.wait(self.repair_interval):
                try:
                    self.repair_once()
                except Exception:
                    # the repair plane must never take the client down;
                    # the next pass retries (faults already counted by
                    # the per-call paths)
                    from advanced_scrapper_tpu.obs import trace

                    trace.record("event", "fleet.repair_error")

        self._repair_thread = threading.Thread(
            target=loop, daemon=True, name=f"astpu-fleet-repair-{self.space}"
        )
        self._repair_thread.start()

    def stop_repair(self) -> None:
        self._repair_stop.set()
        t = self._repair_thread
        if t is not None:
            t.join(timeout=5)
            self._repair_thread = None

    # -- elastic reshard: live N→M cutover --------------------------------

    #: upper bound a write intersecting the arc-under-flip waits at the
    #: gate.  Proceeding past it is SAFE (a late dual-write applies to
    #: both owners; any transient divergence fails the digest check and
    #: retries) — the bound only stops a wedged flip from deadlocking the
    #: write path.
    GATE_WAIT_S = 30.0

    @staticmethod
    def _spec_string(spec: FleetSpec) -> str:
        """Canonical wire form of a topology — the ledger's identity check
        (a resumed reshard must be THE reshard the WAL recorded)."""
        return ";".join(
            "|".join(f"{h}:{p}" for h, p in nodes) for nodes in spec.shards
        )

    def _grow_shards(self, new_spec: FleetSpec) -> None:
        """Extend the live topology with the new spec's extra shards
        (scale-out).  Shard ids present in both specs must keep their
        replica sets — moving a shard's NODES is the repair/restore
        plane's job; a reshard only moves ring arcs between shards."""
        for sid in range(min(len(self._shards), new_spec.num_shards)):
            if new_spec.shards[sid] != self.spec.shards[sid]:
                raise ValueError(
                    f"reshard cannot move shard {sid}'s replica set "
                    f"({self.spec.shards[sid]} → {new_spec.shards[sid]}); "
                    "node replacement is repair/restore, not reshard"
                )
        from advanced_scrapper_tpu.obs import telemetry

        for sid in range(len(self._shards), new_spec.num_shards):
            self._shards.append(
                _Shard(
                    sid=sid,
                    nodes=[
                        _Node(
                            address=addr,
                            client=RpcClient(
                                addr,
                                timeout=self.timeout,
                                retries=self._retries,
                                connect=self._connect,
                                seed=self._seed * 1000 + sid * 10 + k,
                                overload_wait_cap=self.overload_backoff_cap,
                            ),
                        )
                        for k, addr in enumerate(new_spec.shards[sid])
                    ],
                )
            )
            for method in ("probe", "insert"):
                self._m_rpc_s.setdefault(
                    (sid, method),
                    telemetry.histogram(
                        "astpu_fleet_rpc_seconds",
                        "per-shard RPC wall clock, by method",
                        fleet=self._fid, shard=str(sid), method=method,
                    ),
                )

    def _resume_reshard(self) -> None:
        """Client (re)start: adopt an in-flight migration WAL.

        Flipped/retired ranges keep their new owner — the flip write was
        the commit point, sealed strictly after the digest match proved
        the data on the next owner.  Every dual-write window caught open
        is VOIDED back to ``pending``: unsealed progress never counts
        (the armed-ledger discipline the resync path uses).  Routing
        honors the adopted states immediately; the migration itself
        continues when ``reshard_to`` runs again."""
        from advanced_scrapper_tpu.index import reshard as _rs

        path = _rs.ledger_path(self.spill_dir, self.space)
        try:
            ledger = _rs.ReshardLedger.load(path, fs=self._fs)
        except (OSError, ValueError, KeyError):
            return  # unreadable/foreign ledger: surfaced when reshard_to runs
        if ledger is None or ledger.phase != "active":
            return
        if ledger.doc.get("old_spec") != self._spec_string(self.spec):
            return  # a different topology's WAL; not ours to resume
        voided = ledger.void_unflipped()
        new_spec = FleetSpec.parse(ledger.doc["new_spec"])
        self._grow_shards(new_spec)
        table = _rs.RangeTable(ledger.ranges)
        metrics = _rs.reshard_metrics(self._fid)
        if voided:
            metrics["voids"].inc(voided)
        _rs.register_state_gauges(self._fid, table)
        for r in ledger.ranges:
            # every dst is owed an unretire (its arc may be handed-off
            # residue from an earlier topology round trip); sealed arcs
            # re-enter the src's handed-off set
            self._unretired[int(r["dst"])] = interval_add(
                self._unretired.get(int(r["dst"]), []), r["lo"], r["hi"]
            )
            if r["state"] in ("flipped", "retired"):
                self._retired[int(r["src"])] = interval_add(
                    self._retired.get(int(r["src"]), []), r["lo"], r["hi"]
                )
        self._reshard_dirty.update(range(len(self._shards)))
        self._reshard = {
            "table": table,
            "ledger": ledger,
            "old_n": int(ledger.doc["old_n"]),
            "new_n": int(ledger.doc["new_n"]),
            "new_spec": new_spec,
            "metrics": metrics,
            "voided": voided,
        }

    def _route(self, flat: np.ndarray) -> tuple[np.ndarray, np.ndarray | None]:
        """Owning shard per key: the plain ring — unless a reshard is
        live, in which case the range table decides per the cutover
        lifecycle.  Returns ``(primary, dual)``; ``dual`` names each
        key's NEXT owner during its arc's dual-write window (-1 outside
        one, ``None`` when no reshard is running)."""
        rs = self._reshard
        if rs is None:
            return ring_assign(flat, self._route_shards, self.vnodes), None
        from advanced_scrapper_tpu.index.reshard import route_keys

        return route_keys(
            flat, rs["table"], rs["old_n"], rs["new_n"], self.vnodes
        )

    def _gate_wait(self, keys: np.ndarray) -> None:
        """Hold a write that intersects the arc being flipped until the
        cutover releases the gate (bounded by ``GATE_WAIT_S`` — see the
        constant's note on why proceeding late is safe)."""
        if self._gate is None:
            return
        deadline = time.monotonic() + self.GATE_WAIT_S
        with self._gate_cv:
            while self._gate is not None:
                lo, hi = self._gate
                if not range_mask(keys, [(lo, hi)]).any():
                    return
                left = deadline - time.monotonic()
                if left <= 0:
                    return
                self._gate_cv.wait(timeout=min(left, 0.1))

    def _sync_reshard_node(self, sh: _Shard, node: _Node) -> None:
        """Re-assert this client's reshard verdicts on one node — the
        rejoin half of the control plane.  A node that was dark when the
        cutover told it to retire/unretire an arc (or drop its fence
        mark) hears it here; every call is idempotent, every failure
        re-queues via the dirty set."""
        sid = sh.sid
        try:
            for lo, hi in self._retired.get(sid, ()):
                self._node_call(
                    sh, node, "retire_range",
                    {"space": self.space, "lo": lo, "hi": hi},
                    budget=self.timeout,
                )
            for lo, hi in self._unretired.get(sid, ()):
                self._node_call(
                    sh, node, "unretire_range",
                    {"space": self.space, "lo": lo, "hi": hi},
                    budget=self.timeout,
                )
            if self._reshard is None:
                self._node_call(
                    sh, node, "reshard_mark", {"op": "clear"},
                    budget=self.timeout,
                )
            else:
                self._node_call(
                    sh, node, "reshard_mark",
                    {"op": "set", "token": self._token},
                    budget=self.timeout,
                )
        except (RpcUnavailable, RpcOverloaded):
            self._reshard_dirty.add(sid)

    def _broadcast_mark(self, op: str) -> None:
        """Best-effort reshard fence on every live node (``set`` while a
        migration is in flight, ``clear`` at completion) — what
        ``tools/fleet_snapshot.py`` checks before trusting a fence.
        Nodes missed here catch up through the rejoin/dirty sync."""
        for sh in self._shards:
            ok = True
            for node in sh.live_nodes():
                try:
                    header = {"op": op}
                    if op == "set":
                        header["token"] = self._token
                    self._node_call(
                        sh, node, "reshard_mark", header, budget=self.timeout
                    )
                except (RpcUnavailable, RpcOverloaded):
                    ok = False
            if not ok or any(not n.alive for n in sh.nodes):
                self._reshard_dirty.add(sh.sid)

    def reshard_to(self, new_spec: FleetSpec | str) -> dict:
        """Live-migrate the fleet to ``new_spec`` (N→M shards, split or
        merge) while it keeps answering probes and inserts.

        Per migrating arc, in ring order: unretire the arc on its next
        owner → durably arm the dual-write window (every write from that
        instant applies to BOTH owners; reads stay on the old one) →
        stream the old owner's semantic state across, paged under the
        frame cap → under the write gate, require the arc's mixed bucket
        digest to MATCH on the old owner and every live replica of the
        new one → seal the flip in the migration WAL (THE commit point;
        reads+writes move atomically) → retire the arc on the old owner.

        Crash-safe at any instant: rerunning (or reconstructing the
        client) resumes from the WAL — sealed flips keep their new
        owner, open dual-write windows are voided back to pending.
        Raises when a shard the migration needs is fully dark; the WAL
        stays resumable."""
        new = (
            new_spec if isinstance(new_spec, FleetSpec)
            else FleetSpec.parse(new_spec)
        )
        if not self.spill_dir:
            raise RuntimeError(
                "reshard_to needs a spill_dir: the migration WAL (the "
                "crash-safety of the cutover) lives there"
            )
        from advanced_scrapper_tpu.index import reshard as rs

        with self._reshard_lock:
            if (
                self._reshard is None
                and self._spec_string(new) == self._spec_string(self.spec)
            ):
                return {"ranges": 0, "flips": 0, "migrated_postings": 0,
                        "digest_retries": 0, "voided": 0, "already": True}
            st = self._arm_reshard(new, rs)
            ledger = st["ledger"]
            stats = {
                "ranges": len(ledger.ranges),
                "flips": 0,
                "migrated_postings": 0,
                "digest_retries": 0,
                "voided": int(st.get("voided", 0)),
            }
            self._broadcast_mark("set")
            for i, r in enumerate(ledger.ranges):
                if r["state"] == "retired":
                    continue
                lo, hi = int(r["lo"]), int(r["hi"])
                src, dst = int(r["src"]), int(r["dst"])
                if r["state"] != "flipped":
                    self._migrate_range(st, i, lo, hi, src, dst, stats)
                self._retire_range_src(st, i, lo, hi, src, dst, stats)
            self._finish_reshard(st, new)
        return stats

    def _arm_reshard(self, new: FleetSpec, rs) -> dict:
        """Adopt the in-flight reshard state, or create it (plan + fresh
        migration WAL + grown topology + routing table)."""
        if self._reshard is not None:
            got = self._spec_string(self._reshard["new_spec"])
            if got != self._spec_string(new):
                raise RuntimeError(
                    f"a reshard to {got!r} is already in flight; it must "
                    "finish (rerun it) before targeting another topology"
                )
            return self._reshard
        old_n = self._route_shards
        path = rs.ledger_path(self.spill_dir, self.space)
        stale = rs.ReshardLedger.load(path, fs=self._fs)
        if stale is not None and stale.phase == "active":
            # _resume_reshard didn't adopt it ⇒ its old_spec is not ours:
            # overwriting would orphan that migration's verdicts
            raise RuntimeError(
                f"{path}: an unfinished reshard WAL for a different "
                "topology is present; resolve it first"
            )
        plan = rs.plan_reshard(old_n, new.num_shards, self.vnodes)
        ledger = rs.ReshardLedger.create(
            path,
            old_n=old_n,
            new_n=new.num_shards,
            vnodes=self.vnodes,
            old_spec=self._spec_string(self.spec),
            new_spec=self._spec_string(new),
            space=self.space,
            ranges=plan,
            fs=self._fs,
        )
        self._grow_shards(new)
        table = rs.RangeTable(ledger.ranges)
        metrics = rs.reshard_metrics(self._fid)
        rs.register_state_gauges(self._fid, table)
        st = {
            "table": table,
            "ledger": ledger,
            "old_n": old_n,
            "new_n": new.num_shards,
            "new_spec": new,
            "metrics": metrics,
            "voided": 0,
        }
        self._reshard = st
        from advanced_scrapper_tpu.obs import trace

        trace.record(
            "event", "fleet.reshard_start", old=old_n,
            new=new.num_shards, ranges=len(plan),
        )
        return st

    def _migrate_range(self, st, i, lo, hi, src, dst, stats) -> None:
        """One arc, pending → flipped: arm, stream, digest-verify, seal."""
        table, ledger, metrics = st["table"], st["ledger"], st["metrics"]
        src_sh, dst_sh = self._shards[src], self._shards[dst]
        # the next owner may hold this arc as handed-off residue from an
        # earlier topology (N→M→N): un-retire BEFORE any page lands, or
        # its own insert filter would silently drop the stream
        self._unretired[dst] = interval_add(
            self._unretired.get(dst, []), lo, hi
        )
        self._retired[dst] = interval_sub(self._retired.get(dst, []), lo, hi)
        for node in dst_sh.live_nodes():
            try:
                self._node_call(
                    dst_sh, node, "unretire_range",
                    {"space": self.space, "lo": lo, "hi": hi},
                    budget=self.timeout,
                )
            except (RpcUnavailable, RpcOverloaded):
                self._reshard_dirty.add(dst)
        if table.state(i) == "pending":
            # the LEDGER write precedes the first dual-applied write: a
            # crash between the two leaves a recorded window that moved
            # nothing — voided on resume, nothing unaccounted
            ledger.mark(i, "dual_write")
            table.set_state(i, "dual_write")
        for attempt in range(max(1, self.resync_rounds)):
            src_node = self._ensure_write_target(src_sh)
            if src_node is None:
                raise RpcUnavailable(
                    f"reshard: shard {src} is fully dark; the migration "
                    "WAL stays resumable — rerun when it returns"
                )
            self._stream_range(st, src_sh, src_node, dst_sh, lo, hi, stats)
            if self._flip_range(st, i, lo, hi, src_sh, dst_sh, stats):
                return
            metrics["retries"].inc()
            stats["digest_retries"] += 1
        raise RuntimeError(
            f"reshard: range {i} [{lo:#x},{hi:#x}) did not digest-converge "
            f"after {self.resync_rounds} rounds (WAL resumable)"
        )

    def _stream_range(self, st, src_sh, src_node, dst_sh, lo, hi, stats):
        """Page the arc's semantic state src → dst under the frame cap;
        pushes ride ``_replicated_insert`` so every live dst replica (and
        the gap ledgers of dead ones) receives it."""
        metrics = st["metrics"]
        off = 0
        while True:
            t0 = time.perf_counter()
            h, (k, d) = self._node_call(
                src_sh, src_node, "fetch_range",
                {
                    "space": self.space, "lo": lo, "hi": hi,
                    "offset": off, "limit": self.REPLAY_CHUNK_POSTINGS,
                    "mixed": True,
                },
                budget=self.timeout,
            )
            k = np.asarray(k, np.uint64)
            d = np.asarray(d, np.uint64)
            if k.size:
                rid = (
                    f"mig-{self._token}-{self._fid}-{dst_sh.sid}"
                    f"-{self._next_wid()}"
                )
                self._replicated_insert(dst_sh, k, d, rid)
            metrics["pages"].inc()
            metrics["postings"].inc(int(k.size))
            metrics["page_s"].observe(time.perf_counter() - t0)
            metrics["page_b"].observe(float(k.nbytes + d.nbytes))
            stats["migrated_postings"] += int(k.size)
            off += int(k.size)
            if off >= int(h.get("total", off)) or k.size == 0:
                break

    def _range_digest(self, sh, node, lo, hi):
        _h, (dig, cnt) = self._node_call(
            sh, node, "digest",
            {
                "space": self.space, "bits": self.digest_bits,
                "lo": lo, "hi": hi, "mixed": True,
            },
            budget=self.timeout,
        )
        return np.asarray(dig, np.uint64), np.asarray(cnt, np.uint64)

    def _flip_range(self, st, i, lo, hi, src_sh, dst_sh, stats) -> bool:
        """The two-phase commit's decision point, under the write gate:
        flip iff the old owner and EVERY live replica of the new one
        answer identical mixed digests over the arc — and neither side
        holds un-replayed spill for it (a spilled-but-acked write absent
        from both digests would otherwise flip, then replay into a
        retired range and vanish).  False = not yet; caller re-streams."""
        table, ledger, metrics = st["table"], st["ledger"], st["metrics"]
        with self._gate_cv:
            self._gate = (lo, hi)
            deadline = time.monotonic() + 2 * self.timeout
            while self._inflight > 0 and time.monotonic() < deadline:
                self._gate_cv.wait(timeout=0.05)
        try:
            # replay both sides' spill journals first; pending spill on
            # either side makes the digests meaningless for a flip
            src_node = self._ensure_write_target(src_sh)
            self._ensure_write_target(dst_sh)
            if src_node is None or src_sh.pending or dst_sh.pending:
                return False
            live = dst_sh.live_nodes()
            if not live:
                return False
            want = self._range_digest(src_sh, src_node, lo, hi)
            for node in live:
                got = self._range_digest(dst_sh, node, lo, hi)
                if not (
                    np.array_equal(want[0], got[0])
                    and np.array_equal(want[1], got[1])
                ):
                    return False
            # sealed: the ledger write IS the commit point — a crash
            # after it keeps the flip (the data is proven on the new
            # owner), a crash before it voids the window cleanly
            ledger.mark(i, "flipped")
            table.set_state(i, "flipped")
            metrics["flips"].inc()
            stats["flips"] += 1
            return True
        except (RpcUnavailable, RpcOverloaded):
            return False
        finally:
            with self._gate_cv:
                self._gate = None
                self._gate_cv.notify_all()

    def _retire_range_src(self, st, i, lo, hi, src, dst, stats) -> None:
        """Post-flip: the old owner drops the arc (handed-off manifest
        mark — probes/inserts for it now answer empty there) and the
        verdict is sealed.  Re-run-safe: a crash between flip and here
        re-asserts on resume."""
        table, ledger = st["table"], st["ledger"]
        src_sh = self._shards[src]
        self._retired[src] = interval_add(self._retired.get(src, []), lo, hi)
        self._unretired[src] = interval_sub(
            self._unretired.get(src, []), lo, hi
        )
        for node in src_sh.live_nodes():
            try:
                self._node_call(
                    src_sh, node, "retire_range",
                    {"space": self.space, "lo": lo, "hi": hi},
                    budget=self.timeout,
                )
            except (RpcUnavailable, RpcOverloaded):
                self._reshard_dirty.add(src)
        if any(not n.alive for n in src_sh.nodes):
            self._reshard_dirty.add(src)
        ledger.mark(i, "retired")
        table.set_state(i, "retired")

    def _finish_reshard(self, st, new: FleetSpec) -> None:
        """Every range retired: seal the WAL, swap the routing topology,
        drop the fence marks.  Shard objects beyond a scale-in's new
        count stay open (their stores hold only handed-off residue and
        answer empty) — closing live sockets under in-flight fan-outs is
        not worth an empty probe saved."""
        ledger = st["ledger"]
        if not ledger.all_retired():
            raise RuntimeError("reshard finish with unretired ranges")
        ledger.finish()
        self._route_shards = new.num_shards
        self.spec = new
        self._reshard = None
        self._broadcast_mark("clear")
        from advanced_scrapper_tpu.obs import trace

        trace.record(
            "event", "fleet.reshard_done", shards=new.num_shards,
        )

    # -- RPC fan-out internals --------------------------------------------

    def _node_call(
        self,
        sh: _Shard,
        node: _Node,
        method: str,
        header: dict,
        arrays=(),
        *,
        request_id: str | None = None,
        budget: float | None = None,
    ):
        """One node RPC under the overload-vs-dead discipline:

        - :class:`RpcOverloaded` (the node REFUSED admission — provably
          alive) backs off in place, honoring the retry-after hint,
          bounded by ``budget`` (default ``overload_budget``) seconds;
        - :class:`RpcUnavailable` (deadline/connection fault) is only
          allowed to propagate — and so mark the node dead — when the
          node ALSO fails a ping; a node that still answers pings is
          alive-but-slow and gets the same in-place backoff, because
          failing over a healthy shard under load amplifies the storm
          onto the survivors and can cascade the fleet.

        Raises :class:`RpcOverloaded` when the budget runs out with the
        node still alive (the caller decides: another replica, spill, or
        propagate), :class:`RpcUnavailable` only on true unreachability.
        """
        deadline = time.monotonic() + (
            self.overload_budget if budget is None else budget
        )
        wait = 0.05
        while True:
            try:
                return node.client.call(
                    method,
                    header,
                    arrays,
                    timeout=self.timeout,
                    request_id=request_id,
                )
            except RpcOverloaded as e:
                self._m_overload.inc()
                wait = min(
                    max(e.retry_after, wait), self.overload_backoff_cap
                )
            except RpcUnavailable:
                if not node.client.ping(timeout=self.health_timeout):
                    raise  # truly dark: the caller's failover path owns it
                self._m_slow.inc()
                wait = min(wait * 2, self.overload_backoff_cap)
            if time.monotonic() + wait > deadline:
                raise RpcOverloaded(
                    f"{method} to {node.address[0]}:{node.address[1]} still "
                    "overloaded after the in-place backoff budget",
                    retry_after=wait,
                )
            self._sleep(wait)

    def _shard_probe(
        self, sh: _Shard, keys: np.ndarray, tctx=None
    ) -> np.ndarray:
        """Probe one shard's key subset → int64 min doc per key (-1 miss).
        Prefers the write target (it holds everything acked); falls back
        across replicas; a fully-dark shard answers from the overlay only
        and counts the degradation.

        ``tctx`` is the CALLER's trace context, captured before the
        fan-out (pool threads have no ambient context of their own): the
        per-shard span and every RPC under it stitch into the corpus
        trace, and the latency histogram keeps the trace id as its
        slow-call exemplar."""
        from advanced_scrapper_tpu.obs import trace

        with trace.trace_context(*(tctx or (None, None))):
            with trace.span("fleet.probe", shard=sh.sid, keys=int(keys.size)):
                return self._shard_probe_inner(sh, keys, tctx)

    def _shard_probe_inner(self, sh: _Shard, keys: np.ndarray, tctx) -> np.ndarray:
        t0 = time.perf_counter()
        hist = self._m_rpc_s[(sh.sid, "probe")]
        deadline = time.monotonic() + self.overload_budget
        docs = None
        while docs is None:
            order: list[_Node] = []
            with sh.lock:
                wt = sh.nodes[sh.write_target]
            if wt.alive and not sh.promoting:
                order.append(wt)
            order += [n for n in sh.live_nodes() if n not in order]
            saw_overload = False
            # per-node slice of the budget, NOT the whole remainder: an
            # overloaded write target must not absorb the full 45 s
            # before an idle replica gets a chance — one call-timeout of
            # in-place backoff per node per round, then rotate
            node_budget = max(0.5, min(deadline - time.monotonic(), self.timeout))
            for node in order:
                try:
                    _h, (docs,) = self._node_call(
                        sh, node, "probe", {"space": self.space}, [keys],
                        budget=node_budget,
                    )
                    break
                except RpcOverloaded:
                    # alive but refusing/slow: try the next replica, and
                    # NEVER mark the node dead — an overloaded shard
                    # failed over would cascade the storm
                    saw_overload = True
                except RpcUnavailable:
                    # transport fault with pings also failing: a
                    # deterministic handler error (RpcRemoteError — bad
                    # space, operator typo) must stay LOUD, never quietly
                    # mark a healthy node dead
                    self._note_failure(sh, node)
            if docs is None:
                # promotion may still rescue a replica that was merely
                # unproven
                target = self._ensure_write_target(sh)
                if target is not None and target not in order:
                    try:
                        _h, (docs,) = self._node_call(
                            sh, target, "probe", {"space": self.space},
                            [keys], budget=node_budget,
                        )
                    except RpcOverloaded:
                        saw_overload = True
                    except RpcUnavailable:
                        self._note_failure(sh, target)
            if docs is None:
                if saw_overload and time.monotonic() < deadline:
                    self._sleep(0.05)  # every node overloaded: one more round
                    continue
                self._m_degraded.inc(int(keys.size))
                docs = np.full(keys.shape, -1, np.int64)
        docs = np.asarray(docs, np.int64)
        with sh.lock:
            # O(probed keys) lookups under the lock — never a full-dict
            # copy, which would make every degraded probe O(spill size)
            ov = (
                np.fromiter(
                    (sh.overlay.get(k, -1) for k in keys.tolist()),
                    np.int64, keys.size,
                )
                if sh.overlay
                else None
            )
        if ov is not None:
            hit = ov >= 0
            miss = docs < 0
            docs = np.where(
                hit & miss, ov, np.where(hit, np.minimum(docs, ov), docs)
            )
        hist.observe(time.perf_counter() - t0, trace=tctx[0] if tctx else None)
        return docs

    def _replicated_insert(
        self,
        sh: _Shard,
        keys: np.ndarray,
        docs: np.ndarray,
        rid: str,
        *,
        allow_spill: bool = True,
        tctx=None,
    ) -> bool:
        """Write one shard's postings to EVERY live node (same request
        id).  True iff at least one node — including a freshly promoted
        one — durably applied it; on total failure the batch spills
        (unless this IS the replay path).  Nodes that missed an ACKED
        write get the batch recorded in their gap ledger: they must
        absorb it before they may rejoin (``_try_revive``).

        ``tctx`` restores the caller's trace context on the fan-out pool
        thread (None = inherit whatever is ambient, the direct-call and
        replay paths)."""
        from advanced_scrapper_tpu.obs import trace

        if tctx is None:
            tctx = trace.current_context()
        with trace.trace_context(*(tctx or (None, None))):
            with trace.span(
                "fleet.insert", shard=sh.sid, postings=int(keys.size)
            ):
                return self._replicated_insert_inner(
                    sh, keys, docs, rid, allow_spill, tctx
                )

    def _replicated_insert_inner(
        self, sh, keys, docs, rid, allow_spill, tctx
    ) -> bool:
        t0 = time.perf_counter()
        hist = self._m_rpc_s[(sh.sid, "insert")]
        target = self._ensure_write_target(sh)
        acked_ix: set[int] = set()
        # the overload budget is a PER-CALL bound (the _node_call
        # docstring's promise): slice it across the replica fan-out so a
        # 2-replica overloaded shard stalls one insert ~overload_budget
        # total, not 2× (+ another on the promotion retry)
        n_live = max(1, len(sh.live_nodes()))
        node_budget = max(self.timeout, self.overload_budget / (n_live + 1))
        for ix, node in enumerate(list(sh.nodes)):
            if not node.alive:
                continue
            try:
                self._node_call(
                    sh, node, "insert", {"space": self.space}, [keys, docs],
                    request_id=f"{rid}@{node.address[0]}:{node.address[1]}",
                    budget=node_budget,
                )
                acked_ix.add(ix)
            except RpcOverloaded:
                # alive but refusing past the in-place budget: missed
                # this write — the gap-ledger loop below treats any
                # non-acked node identically (the live-node invariant is
                # unconditional) — but NO failover count, no
                # health-check demotion
                pass
            except RpcUnavailable:
                self._note_failure(sh, node)
        if not acked_ix and target is not None:
            # every node died mid-write: one promotion attempt, then spill
            target = self._ensure_write_target(sh)
            if target is not None:
                try:
                    self._node_call(
                        sh, target, "insert", {"space": self.space},
                        [keys, docs],
                        request_id=f"{rid}@{target.address[0]}:{target.address[1]}",
                        budget=node_budget,
                    )
                    acked_ix.add(sh.nodes.index(target))
                except RpcOverloaded:
                    pass  # still alive: falls through to spill below
                except RpcUnavailable:
                    self._note_failure(sh, target)
        hist.observe(time.perf_counter() - t0, trace=tctx[0] if tctx else None)
        acked = bool(acked_ix)
        if acked:
            with sh.lock:
                for ix in range(len(sh.nodes)):
                    if ix not in acked_ix:
                        # an overloaded node that missed an ACKED write
                        # must still absorb it before it may serve again —
                        # the live-node invariant (live ⇒ holding every
                        # acked posting) holds unconditionally, so
                        # _gap_append sidelines it until the backfill
                        # drains.  With the in-place budget this is the
                        # rare tail, not the storm steady state.
                        self._gap_append(sh, ix, rid, keys, docs)
        elif allow_spill:
            # fully refused (all nodes overloaded) or fully dark: the
            # spill journal absorbs the batch and replays later — counted
            # backpressure, never data loss, and for pure overload the
            # nodes stay alive and unpromoted
            self._spill(sh, keys, docs, rid)
        return acked

    #: per-node gap ledger cap — beyond this many missed postings the
    #: ledger is dropped and the node is routed through a FULL
    #: digest-verified resync before it may rejoin (bounded client RAM,
    #: no node ever sits out forever); instance-overridable via the
    #: ``gap_limit_postings`` constructor knob
    GAP_LIMIT_POSTINGS = 1 << 20

    def _gap_append(self, sh: _Shard, ix: int, rid, keys, docs) -> None:
        """Record an acked write a node missed; caller holds ``sh.lock``.

        If a racing ``_try_revive`` brought the node back between our
        fan-out snapshot and this append, the node is live WITHOUT this
        write — re-kill it so the next revive round drains the ledger;
        the live-node invariant must hold unconditionally.

        Overflowed nodes: with no ledger armed the write is dropped — a
        future resync's full-state push covers it by construction.  A
        resync in flight ARMS a fresh ledger (``_resync_node``) so the
        writes it races with are preserved; if even that ledger overflows
        the resync is voided and restarts."""
        gap = sh.gaps.get(ix)
        if ix in sh.gap_overflow and gap is None:
            return  # awaiting resync; the full-state push will carry this
        if sh.nodes[ix].alive:
            sh.nodes[ix].alive = False
            if sh.nodes[sh.write_target] is sh.nodes[ix]:
                sh.promoting = True
        if gap is None:
            gap = sh.gaps.setdefault(ix, [])
        held = sum(int(k.size) for _r, k, _d in gap)
        if held + int(keys.size) > self.gap_limit_postings:
            sh.gaps.pop(ix, None)
            sh.gap_overflow.add(ix)
            from advanced_scrapper_tpu.obs import telemetry

            telemetry.event_counter(
                "astpu_fleet_gap_overflow_total",
                "nodes whose gap ledger outgrew the cap and was dropped; "
                "they rejoin through digest-verified auto-resync "
                "(astpu_fleet_resync_total), never by the plain drain path",
            ).inc()
            return
        gap.append((rid, keys, docs))

    # -- PersistentIndex API ----------------------------------------------

    def probe_batch(self, keys: np.ndarray) -> np.ndarray:
        """``int64[B]`` earliest candidate doc per query row (-1 = none);
        same contract (and same row-min combination) as the single-node
        index, fanned out per shard in parallel."""
        keys = np.asarray(keys, dtype=np.uint64)
        if keys.ndim == 1:
            keys = keys[:, None]
        B = keys.shape[0]
        if B == 0:
            return np.zeros((0,), np.int64)
        flat = keys.ravel()
        shard_of, _dual = self._route(flat)
        best = np.full(flat.shape, _I64_MAX, np.int64)
        from advanced_scrapper_tpu.obs import trace

        tctx = trace.current_context()  # captured HERE: pool threads have none
        futures = []
        for sid in range(len(self._shards)):
            ix = np.flatnonzero(shard_of == sid)
            if ix.size == 0:
                continue
            futures.append(
                (
                    ix,
                    self._pool.submit(
                        self._shard_probe, self._shards[sid], flat[ix], tctx
                    ),
                )
            )
        for ix, fut in futures:
            docs = fut.result()
            hit = docs >= 0
            best[ix[hit]] = np.minimum(best[ix[hit]], docs[hit])
        best = best.reshape(B, -1).min(axis=1)
        return np.where(best == _I64_MAX, NO_DOC, best)

    def insert_batch(self, keys: np.ndarray, docs: np.ndarray) -> None:
        """Durably append postings, sharded + replicated; a dark shard
        spills instead of raising."""
        keys = np.ascontiguousarray(keys, dtype=np.uint64).ravel()
        docs = np.ascontiguousarray(docs, dtype=np.uint64).ravel()
        if keys.size == 0:
            return
        with self._floor_lock:
            self._floor = max(self._floor, int(docs.max()) + 1)
            self._postings_written += int(keys.size)
        self._gate_wait(keys)
        with self._gate_cv:
            self._inflight += 1
        try:
            shard_of, dual_of = self._route(keys)
            from advanced_scrapper_tpu.obs import trace

            tctx = trace.current_context()
            futures = []
            for sid in range(len(self._shards)):
                ix = np.flatnonzero(shard_of == sid)
                if ix.size:
                    sh = self._shards[sid]
                    rid = (
                        f"ins-{self._token}-{self._fid}-{sid}"
                        f"-{self._next_wid()}"
                    )
                    futures.append(
                        self._pool.submit(
                            self._replicated_insert,
                            sh, keys[ix], docs[ix], rid, tctx=tctx,
                        )
                    )
                if dual_of is None:
                    continue
                # dual-write window: the arc's NEXT owner gets the same
                # postings, first-class (gap ledger / spill discipline
                # included) — idempotent server inserts make any overlap
                # with the migration stream harmless
                dx = np.flatnonzero(dual_of == sid)
                if dx.size:
                    rs = self._reshard
                    if rs is not None:
                        rs["metrics"]["dual"].inc(int(dx.size))
                    rid = (
                        f"dual-{self._token}-{self._fid}-{sid}"
                        f"-{self._next_wid()}"
                    )
                    futures.append(
                        self._pool.submit(
                            self._replicated_insert,
                            self._shards[sid], keys[dx], docs[dx], rid,
                            tctx=tctx,
                        )
                    )
            for fut in futures:
                fut.result()
        finally:
            with self._gate_cv:
                self._inflight -= 1
                self._gate_cv.notify_all()

    _wid_lock = threading.Lock()
    _wid = 0

    def _next_wid(self) -> int:
        with ShardedIndexClient._wid_lock:
            ShardedIndexClient._wid += 1
            return ShardedIndexClient._wid

    def check_and_add_batch(
        self, keys: np.ndarray, doc_ids: np.ndarray
    ) -> np.ndarray:
        """Sharded stream step, byte-equal to the single-node oracle:
        fan-out probe → the SHARED intra-batch resolution
        (:func:`~.store.resolve_intra_batch`) → replicated insert of the
        fresh rows' postings."""
        keys = np.asarray(keys, dtype=np.uint64)
        if keys.ndim == 1:
            keys = keys[:, None]
        doc_ids = np.ascontiguousarray(doc_ids, dtype=np.uint64).ravel()
        B, nb = keys.shape
        if B != doc_ids.size:
            raise ValueError(f"{B} key rows vs {doc_ids.size} doc ids")
        attr = resolve_intra_batch(
            keys, doc_ids, np.asarray(self.probe_batch(keys))
        )
        fresh = attr < 0
        if fresh.any():
            self.insert_batch(
                keys[fresh].ravel(), np.repeat(doc_ids[fresh], nb)
            )
        return attr

    def allocate_doc_ids(self, n: int) -> np.ndarray:
        """Monotonic uint64 ids from shard 0's durable allocator, floored
        by the client-side high water (so failover to a lagging replica
        can never reissue an id this client already referenced).  A fully
        dark shard 0 degrades to local allocation from the high water —
        but ONLY once this client has synced a durable floor at least
        once this session: a fresh client that never reached the
        allocator would otherwise restart at 0 and alias ids the fleet
        already holds from earlier runs, silently re-pointing historical
        attributions.  With no synced floor the darkness is an error."""
        sh = self._shards[0]
        with self._floor_lock:
            floor = self._floor
            floor_known = self._floor_known
        target = self._ensure_write_target(sh)
        ids = None
        if target is not None:
            try:
                _h, (ids,) = self._node_call(
                    sh, target, "allocate",
                    {"space": self.space, "n": int(n), "floor": floor},
                )
            except RpcOverloaded:
                pass  # alive but refusing: degrade like darkness below,
                #       WITHOUT marking the allocator shard dead
            except RpcUnavailable:
                self._note_failure(sh, target)
        synced = ids is not None
        if ids is None and not floor_known:
            raise RpcUnavailable(
                f"cannot allocate doc ids for space {self.space!r}: shard 0 "
                "is unreachable and no durable id floor was ever synced — "
                "local allocation could reissue ids the fleet already holds"
            )
        if ids is None:
            ids = np.arange(floor, floor + int(n), dtype=np.uint64)
        ids = np.asarray(ids, np.uint64)
        with self._floor_lock:
            if synced:
                self._floor_known = True
            self._floor = max(self._floor, int(ids.max()) + 1 if ids.size else 0)
        return ids

    def posting_count(self) -> int:
        """Postings THIS client wrote (acked or spilled) — the cheap gauge
        accessor; a fleet-wide census would be an RPC fan-out per metrics
        scrape (use :meth:`stats` for that, deliberately)."""
        with self._floor_lock:
            return self._postings_written

    def doc_id_floor(self) -> int:
        sh = self._shards[0]
        target = self._ensure_write_target(sh)
        if target is not None:
            try:
                h, _ = self._node_call(sh, target, "floor", {"space": self.space})
                with self._floor_lock:
                    self._floor_known = True
                    self._floor = max(self._floor, int(h["floor"]))
            except RpcOverloaded:
                pass  # keep the cached floor; never a death signal
            except RpcUnavailable:
                self._note_failure(sh, target)
        with self._floor_lock:
            return self._floor

    def raise_doc_id_floor(self, floor: int) -> None:
        with self._floor_lock:
            self._floor = max(self._floor, int(floor))

    def log_names(self, doc_ids, names) -> None:
        """Best-effort docmap on shard 0 (attribution-only, like local)."""
        sh = self._shards[0]
        target = self._ensure_write_target(sh)
        if target is None:
            return
        try:
            self._node_call(
                sh, target, "log_names",
                {"space": self.space, "names": [str(x) for x in names]},
                [np.asarray(doc_ids, np.uint64)],
            )
        except RpcOverloaded:
            pass  # best-effort sidecar: drop under overload, stay alive
        except RpcUnavailable:
            self._note_failure(sh, target)

    def checkpoint(self) -> None:
        """Fan the durability point to every live node; spill journals
        are already fsync'd at append time.  Also the periodic recovery
        probe: a dark shard that came back replays its spill here, and a
        gap-OVERFLOWED node gets its digest-verified resync attempt —
        checkpoint cadence is the hot-path-safe place for that streaming
        work (the backend already calls it at its durability cadence)."""
        for sh in self._shards:
            if any(not n.alive for n in sh.nodes):
                self._try_revive(sh, allow_resync=True)
            if sh.pending or not sh.live_nodes():
                self._ensure_write_target(sh)
            for node in sh.live_nodes():
                try:
                    self._node_call(
                        sh, node, "checkpoint", {"space": self.space},
                        budget=self.timeout,
                    )
                except RpcOverloaded:
                    pass  # durability point deferred, node NOT dead
                except RpcUnavailable:
                    self._note_failure(sh, node)

    def dump_postings(self) -> tuple[np.ndarray, np.ndarray]:
        """Every live posting across the fleet + the un-replayed overlay —
        the crashsweep verification surface, same contract as local.
        Paged (``REPLAY_CHUNK_POSTINGS`` per RPC) so a grown shard never
        produces a frame past the cap; meant to run quiescently — pages
        are not one snapshot under concurrent inserts."""
        parts_k, parts_d = [], []
        for sh in self._shards:
            target = self._ensure_write_target(sh)
            if target is not None:
                try:
                    off = 0
                    while True:
                        h, (k, d) = self._node_call(
                            sh, target, "dump",
                            {
                                "space": self.space,
                                "offset": off,
                                "limit": self.REPLAY_CHUNK_POSTINGS,
                            },
                        )
                        parts_k.append(np.asarray(k, np.uint64))
                        parts_d.append(np.asarray(d, np.uint64))
                        off += int(np.asarray(k).size)
                        if off >= int(h.get("total", off)) or np.asarray(k).size == 0:
                            break
                except RpcOverloaded:
                    pass  # partial dump; verification reruns quiescently
                except RpcUnavailable:
                    self._note_failure(sh, target)
            with sh.lock:
                for _rid, k, d in sh.pending:
                    parts_k.append(k)
                    parts_d.append(d)
        if not parts_k:
            e = np.zeros((0,), np.uint64)
            return e, e
        return np.concatenate(parts_k), np.concatenate(parts_d)

    def stats(self) -> dict:
        out = {"space": self.space, "shards": []}
        for sh in self._shards:
            target = self._ensure_write_target(sh)
            st = None
            if target is not None:
                try:
                    st, _ = self._node_call(
                        sh, target, "stats", {"space": self.space},
                        budget=self.timeout,
                    )
                except RpcOverloaded:
                    pass
                except RpcUnavailable:
                    self._note_failure(sh, target)
            out["shards"].append(st)
        return out

    def wipe(self) -> int:
        """Expire every posting of this wipe-allowed space fleet-wide;
        returns the total dropped count.

        Refused client-side (and again server-side) for any space whose
        :func:`~advanced_scrapper_tpu.index.remote.namespace_policy` does
        not declare ``wipe_allowed`` (``canary:`` probe expiry and
        ``tenant:`` offboarding qualify) — expiry must be structurally
        unable to touch real postings.  Fans
        to EVERY node of every shard, not just the write target: replicas
        hold synchronously replicated copies, and a wipe that missed one
        would resurrect canary postings at the next failover.  A node
        that is down or overloaded is skipped (its copy is wiped when the
        next round's wipe reaches it; canary spaces are never repaired
        back).  Pending spill entries for the space are dropped too — a
        replayed canary posting after expiry would be pollution."""
        if not namespace_policy(self.space).wipe_allowed:
            raise ValueError(
                f"wipe is restricted to wipe-allowed namespace prefixes "
                f"({CANARY_SPACE_PREFIX!r}, tenant spaces), not "
                f"{self.space!r}"
            )
        dropped = 0
        for sh in self._shards:
            with sh.lock:
                sh.pending.clear()
                sh.overlay.clear()
            for node in sh.nodes:
                if not node.alive:
                    continue
                try:
                    h, _ = self._node_call(
                        sh, node, "wipe", {"space": self.space},
                        budget=self.timeout,
                    )
                    dropped += int(h.get("dropped", 0))
                except RpcOverloaded:
                    pass
                except RpcUnavailable:
                    self._note_failure(sh, node)
        return dropped

    def for_space(self, space: str, *, spill_dir: str | None = None):
        """A sibling client over the SAME topology for another key space
        — the canary prober's entry point: given the live fleet client,
        build the isolated ``canary:…`` namespace client without
        re-plumbing addresses.  Construction knobs are replayed exactly
        (the ctor saved them for topology growth); the spill journal
        defaults OFF — synthetic canary postings must never durably
        journal into a real spill directory."""
        return ShardedIndexClient(
            self.spec,
            space=space,
            spill_dir=spill_dir,
            timeout=self.timeout,
            retries=self._retries,
            health_checks=self.health_checks,
            health_timeout=self.health_timeout,
            vnodes=self.vnodes,
            connect=self._connect,
            seed=self._seed,
            fs=self._fs,
            overload_backoff_cap=self.overload_backoff_cap,
            overload_budget=self.overload_budget,
            sleep=self._sleep,
            gap_limit_postings=self.gap_limit_postings,
            repair_interval=0,
            resync_rounds=self.resync_rounds,
            digest_bits=self.digest_bits,
        )

    def close(self) -> None:
        """Release sockets + journals.  Spilled-but-unreplayed postings
        stay in the on-disk journal for the next client's
        ``_reload_spill`` — close is NOT a drop."""
        if self._closed:
            return
        self._closed = True
        self.stop_repair()
        self._pool.shutdown(wait=True)
        for sh in self._shards:
            if sh.journal is not None:
                sh.journal.close()
                sh.journal = None
            for node in sh.nodes:
                node.client.close()
