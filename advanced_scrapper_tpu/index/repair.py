"""Anti-entropy primitives: key-space digests over the index's semantic state.

Replicas of one shard can silently diverge three ways: a node missed writes
while dark (gap ledger dropped past its cap), a scrub quarantined a
bit-rotted segment (postings deliberately withdrawn rather than served
corrupt), or an operator restored one node from an older snapshot.  The
repair plane needs to find the divergence WITHOUT streaming whole indexes
around — that is this module: a Merkle-style two-level digest over the
uint64 key space.

The digested representation is the **semantic state** — sorted unique keys
with the minimum doc id each attributes to (``PersistentIndex
.semantic_items``) — because that is the only thing probes can observe:
posting multiplicity and compaction timing differ between healthy replicas
by construction and must cancel out of the comparison.

Shape: the key space splits into ``2**bits`` buckets by the key's top bits
(keys are already hashes, so buckets are uniform); each bucket folds to a
64-bit XOR of a mixed ``(key, min-doc)`` hash plus a key count.  Two
replicas agree ⇔ every bucket's ``(digest, count)`` pair agrees; a
divergent bucket names a key RANGE small enough to stream (the
``fetch_range`` RPC, paged under the frame cap).  XOR-folding makes the
digest order-independent and incrementally recomputable, and a single
changed pair flips the bucket with probability 1 − 2⁻⁶⁴.

Pure numpy — importable by both halves of the fleet (client
``index/fleet.py``, server ``index/remote.py``) and by offline tools.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "DEFAULT_BITS",
    "KEY_SPACE_END",
    "bucket_digests",
    "bucket_range",
    "interval_add",
    "interval_sub",
    "mix64",
    "range_mask",
    "semantic_min",
]

#: default digest resolution: 256 buckets ≈ 1/256th of a shard per
#: divergent-range transfer — coarse enough that a digest frame is tiny
#: (4 KiB), fine enough that healing one rotted segment never re-streams
#: the whole shard
DEFAULT_BITS = 8

#: exclusive end of the uint64 key space (2**64 — kept a Python int:
#: range arithmetic would overflow uint64)
KEY_SPACE_END = 1 << 64


def semantic_min(keys: np.ndarray, docs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Collapse raw postings to the semantic state: sorted unique keys +
    min doc id per key (what a probe answers with)."""
    keys = np.ascontiguousarray(keys, np.uint64).ravel()
    docs = np.ascontiguousarray(docs, np.uint64).ravel()
    if keys.size == 0:
        return keys, docs
    order = np.lexsort((docs, keys))
    keys, docs = keys[order], docs[order]
    first = np.empty(keys.size, bool)
    first[0] = True
    first[1:] = keys[1:] != keys[:-1]
    return keys[first], docs[first]


def mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — maps raw keys to their RING POSITION.  The
    consistent-hash ring (``fleet.ring_assign``) and the reshard migration
    ranges both live in this mixed space, so every module that slices the
    space per-owner (fleet, reshard, the server's mixed digest/fetch modes)
    must share the one definition."""
    x = np.ascontiguousarray(x, np.uint64)
    with np.errstate(over="ignore"):
        x = x.copy()
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return x


def range_mask(keys: np.ndarray, ranges) -> np.ndarray:
    """Boolean mask of ``keys`` whose RING POSITION (``mix64``) falls in
    any ``[lo, hi)`` of ``ranges`` (Python-int bounds; ``hi`` ≥
    ``KEY_SPACE_END`` means "to the end of the space")."""
    keys = np.ascontiguousarray(keys, np.uint64).ravel()
    mask = np.zeros(keys.size, bool)
    if not keys.size:
        return mask
    pos = mix64(keys)
    for lo, hi in ranges:
        m = pos >= np.uint64(lo)
        if int(hi) < KEY_SPACE_END:
            m &= pos < np.uint64(hi)
        mask |= m
    return mask


def _mix_pair(keys: np.ndarray, docs: np.ndarray) -> np.ndarray:
    """64-bit hash per (key, doc) pair — splitmix64 finalizer over an
    odd-multiplier combine, so equal multisets XOR to equal digests and a
    single differing pair flips the fold."""
    with np.errstate(over="ignore"):
        x = keys ^ (docs * np.uint64(0x9E3779B97F4A7C15))
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return x


def bucket_digests(
    keys: np.ndarray,
    docs: np.ndarray,
    bits: int = DEFAULT_BITS,
    positions: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """``(digests u64[2**bits], counts u64[2**bits])`` over a SEMANTIC
    ``(key → min doc)`` state (callers pass :func:`semantic_min` output —
    raw postings would make healthy replicas look divergent).

    ``positions`` buckets each pair by an alternate coordinate (same
    length as ``keys``) instead of the raw key — the reshard plane passes
    ``mix64(keys)`` so digests compare per RING RANGE; the fold itself
    still mixes the raw ``(key, doc)`` pair, so the two bucketings answer
    over the identical underlying state."""
    nb = 1 << int(bits)
    dig = np.zeros(nb, np.uint64)
    cnt = np.zeros(nb, np.uint64)
    keys = np.ascontiguousarray(keys, np.uint64).ravel()
    docs = np.ascontiguousarray(docs, np.uint64).ravel()
    if keys.size:
        coord = keys if positions is None else np.ascontiguousarray(
            positions, np.uint64
        ).ravel()
        b = (coord >> np.uint64(64 - int(bits))).astype(np.int64)
        np.bitwise_xor.at(dig, b, _mix_pair(keys, docs))
        np.add.at(cnt, b, np.uint64(1))
    return dig, cnt


def interval_add(ranges, lo: int, hi: int) -> list[tuple[int, int]]:
    """Add ``[lo, hi)`` to a list of disjoint sorted intervals, merging
    overlaps/adjacency; Python-int bounds (``hi`` may be 2**64).  The
    store's handed-off ledger rides this: retiring a range twice, or
    retiring two arcs that touch, must collapse to one interval so
    manifests stay canonical."""
    lo, hi = int(lo), int(hi)
    ivs = sorted([(int(a), int(b)) for a, b in ranges] + ([(lo, hi)] if hi > lo else []))
    out: list[tuple[int, int]] = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def interval_sub(ranges, lo: int, hi: int) -> list[tuple[int, int]]:
    """Subtract ``[lo, hi)`` from a list of disjoint intervals — how a
    node un-retires a range it is RE-acquiring (an N→M→N round trip hands
    an arc back to its original owner, whose handed-off ledger must stop
    dropping inserts for it)."""
    lo, hi = int(lo), int(hi)
    out: list[tuple[int, int]] = []
    for a, b in sorted((int(a), int(b)) for a, b in ranges):
        if b <= lo or a >= hi:
            out.append((a, b))
            continue
        if a < lo:
            out.append((a, lo))
        if b > hi:
            out.append((hi, b))
    return out


def bucket_range(bucket: int, bits: int = DEFAULT_BITS) -> tuple[int, int]:
    """``[lo, hi)`` uint64 key range owned by ``bucket`` (``hi`` may be
    ``KEY_SPACE_END`` — Python ints, since 2**64 overflows uint64)."""
    width = 1 << (64 - int(bits))
    lo = int(bucket) * width
    return lo, lo + width
