"""Anti-entropy primitives: key-space digests over the index's semantic state.

Replicas of one shard can silently diverge three ways: a node missed writes
while dark (gap ledger dropped past its cap), a scrub quarantined a
bit-rotted segment (postings deliberately withdrawn rather than served
corrupt), or an operator restored one node from an older snapshot.  The
repair plane needs to find the divergence WITHOUT streaming whole indexes
around — that is this module: a Merkle-style two-level digest over the
uint64 key space.

The digested representation is the **semantic state** — sorted unique keys
with the minimum doc id each attributes to (``PersistentIndex
.semantic_items``) — because that is the only thing probes can observe:
posting multiplicity and compaction timing differ between healthy replicas
by construction and must cancel out of the comparison.

Shape: the key space splits into ``2**bits`` buckets by the key's top bits
(keys are already hashes, so buckets are uniform); each bucket folds to a
64-bit XOR of a mixed ``(key, min-doc)`` hash plus a key count.  Two
replicas agree ⇔ every bucket's ``(digest, count)`` pair agrees; a
divergent bucket names a key RANGE small enough to stream (the
``fetch_range`` RPC, paged under the frame cap).  XOR-folding makes the
digest order-independent and incrementally recomputable, and a single
changed pair flips the bucket with probability 1 − 2⁻⁶⁴.

Pure numpy — importable by both halves of the fleet (client
``index/fleet.py``, server ``index/remote.py``) and by offline tools.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "DEFAULT_BITS",
    "KEY_SPACE_END",
    "bucket_digests",
    "bucket_range",
    "semantic_min",
]

#: default digest resolution: 256 buckets ≈ 1/256th of a shard per
#: divergent-range transfer — coarse enough that a digest frame is tiny
#: (4 KiB), fine enough that healing one rotted segment never re-streams
#: the whole shard
DEFAULT_BITS = 8

#: exclusive end of the uint64 key space (2**64 — kept a Python int:
#: range arithmetic would overflow uint64)
KEY_SPACE_END = 1 << 64


def semantic_min(keys: np.ndarray, docs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Collapse raw postings to the semantic state: sorted unique keys +
    min doc id per key (what a probe answers with)."""
    keys = np.ascontiguousarray(keys, np.uint64).ravel()
    docs = np.ascontiguousarray(docs, np.uint64).ravel()
    if keys.size == 0:
        return keys, docs
    order = np.lexsort((docs, keys))
    keys, docs = keys[order], docs[order]
    first = np.empty(keys.size, bool)
    first[0] = True
    first[1:] = keys[1:] != keys[:-1]
    return keys[first], docs[first]


def _mix_pair(keys: np.ndarray, docs: np.ndarray) -> np.ndarray:
    """64-bit hash per (key, doc) pair — splitmix64 finalizer over an
    odd-multiplier combine, so equal multisets XOR to equal digests and a
    single differing pair flips the fold."""
    with np.errstate(over="ignore"):
        x = keys ^ (docs * np.uint64(0x9E3779B97F4A7C15))
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return x


def bucket_digests(
    keys: np.ndarray, docs: np.ndarray, bits: int = DEFAULT_BITS
) -> tuple[np.ndarray, np.ndarray]:
    """``(digests u64[2**bits], counts u64[2**bits])`` over a SEMANTIC
    ``(key → min doc)`` state (callers pass :func:`semantic_min` output —
    raw postings would make healthy replicas look divergent)."""
    nb = 1 << int(bits)
    dig = np.zeros(nb, np.uint64)
    cnt = np.zeros(nb, np.uint64)
    keys = np.ascontiguousarray(keys, np.uint64).ravel()
    docs = np.ascontiguousarray(docs, np.uint64).ravel()
    if keys.size:
        b = (keys >> np.uint64(64 - int(bits))).astype(np.int64)
        np.bitwise_xor.at(dig, b, _mix_pair(keys, docs))
        np.add.at(cnt, b, np.uint64(1))
    return dig, cnt


def bucket_range(bucket: int, bits: int = DEFAULT_BITS) -> tuple[int, int]:
    """``[lo, hi)`` uint64 key range owned by ``bucket`` (``hi`` may be
    ``KEY_SPACE_END`` — Python ints, since 2**64 overflows uint64)."""
    width = 1 << (64 - int(bits))
    lo = int(bucket) * width
    return lo, lo + width
