"""Structured span tracing + in-memory crash flight recorder.

Metrics (``obs/telemetry.py``) answer "how fast, how many"; this module
answers "what exactly was in flight when it died".  A fixed-size ring
buffer records spans and events — batch/trace IDs flow from document
ingest through encode → H2D → kernel dispatch → resolve → matcher — and
on a crash or a chaos-injected fault the ring is dumped to a JSONL
sidecar, so PR 1's kill-restart harness (``tools/crashsweep.py``) can
assert on the recorder's last-known state instead of reconstructing it
from log lines.

Recording is OFF unless ``ASTPU_TELEMETRY`` is truthy or
``ASTPU_FLIGHT_RECORDER=<path>`` names a dump destination (the env knob
forked children inherit, mirroring ``ASTPU_CHAOS_FS``).  Disabled,
:func:`span` costs one attribute check before yielding.

The dump path deliberately bypasses the ``storage.fsio`` seam: the
recorder fires *during* injected storage faults, and routing its own
sidecar through the faulty substrate would recurse the injection (and
torn flight logs defeat their purpose).  Every record line is
self-contained JSON, so even a tail cut off by the OS stays parseable
line-by-line.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

__all__ = [
    "FlightRecorder",
    "RECORDER",
    "enabled",
    "set_enabled",
    "set_dump_path",
    "dump_path",
    "span",
    "record",
    "new_trace_id",
    "new_span_id",
    "current_context",
    "current_trace_id",
    "trace_context",
    "wire_context",
    "context_from_wire",
    "dump",
    "dump_on_fault",
    "add_fault_hook",
    "install_excepthook",
]

#: callbacks run (once, with the recorder) inside :meth:`dump_on_fault`
#: BEFORE the sidecar is written — how the stage-graph runtime lands a
#: whole-graph drain snapshot in the ring at the kill point without this
#: module importing the runtime (the hook is registered BY the runtime).
_FAULT_HOOKS: list = []


def add_fault_hook(fn) -> None:
    """Register ``fn(recorder)`` to run on the crash path.  Hooks must be
    fast and must never raise (they run while the process is dying)."""
    if fn not in _FAULT_HOOKS:
        _FAULT_HOOKS.append(fn)

_TRUTHY = ("1", "true", "yes", "on")

_trace_ids = itertools.count(1)
_span_ids = itertools.count(1)


def new_trace_id() -> str:
    """Process-unique trace id (pid-qualified so multi-process sweeps can
    interleave their sidecars without collision)."""
    return f"{os.getpid():x}-{next(_trace_ids):x}"


def new_span_id() -> str:
    """Process-unique span id (same pid-qualified scheme as trace ids:
    server-side spans of a propagated trace are minted in ANOTHER
    process, and the stitched view must never alias two of them)."""
    return f"s{os.getpid():x}.{next(_span_ids):x}"


# -- trace context -----------------------------------------------------------
#
# The ambient (trace_id, span_id) pair, carried by contextvars so it flows
# through nested spans on one thread but NOT across threads or sockets by
# accident — a server-side span whose trace id matches a client's proves the
# id travelled over the wire (the RPC ``_trace`` header), not through
# shared process state.  ``span`` inherits and extends the context; RPC
# clients serialize it with :func:`wire_context` and servers restore it
# with :func:`context_from_wire`.

_CTX: contextvars.ContextVar = contextvars.ContextVar("astpu_trace", default=None)


def current_context() -> tuple[str, str] | None:
    """The ambient ``(trace_id, span_id)`` pair, or None outside a trace."""
    return _CTX.get()


def current_trace_id() -> str | None:
    ctx = _CTX.get()
    return ctx[0] if ctx else None


@contextmanager
def trace_context(trace_id: str | None, span_id: str | None = None):
    """Run the body under an explicit trace context (the server-side
    entry point: restore a propagated context, or start a fresh corpus
    trace).  ``trace_id=None`` clears the context for the body."""
    if trace_id is None:
        token = _CTX.set(None)
    else:
        token = _CTX.set((trace_id, span_id or new_span_id()))
    try:
        yield
    finally:
        _CTX.reset(token)


def wire_context() -> dict | None:
    """The ambient context as a JSON-able header fragment (``None`` when
    there is nothing to propagate) — what RPC/lease clients attach."""
    ctx = _CTX.get()
    if ctx is None:
        return None
    return {"t": ctx[0], "s": ctx[1]}


def context_from_wire(frag) -> tuple[str, str] | None:
    """Parse a :func:`wire_context` fragment from a request header;
    malformed fragments (an old peer, a fuzzer) are dropped, never raised
    — trace propagation must not be able to fail a request."""
    if not isinstance(frag, dict):
        return None
    t, s = frag.get("t"), frag.get("s")
    if not isinstance(t, str) or not t:
        return None
    return (t, s if isinstance(s, str) and s else new_span_id())


class FlightRecorder:
    """Bounded ring of structured events; thread-safe; cheap when off."""

    def __init__(self, capacity: int = 2048):
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._active: bool | None = None  # None → resolve from env lazily
        self._dump_path: str | None = None
        self._dumped = False
        self.capacity = capacity

    # -- gating ------------------------------------------------------------

    @property
    def active(self) -> bool:
        if self._active is None:
            env = os.environ
            self._active = (
                env.get("ASTPU_TELEMETRY", "").lower() in _TRUTHY
                or bool(env.get("ASTPU_FLIGHT_RECORDER"))
            )
        return self._active

    def set_active(self, on: bool | None) -> None:
        self._active = on

    def set_dump_path(self, path: str | None) -> None:
        self._dump_path = path

    def dump_path(self) -> str | None:
        return self._dump_path or os.environ.get("ASTPU_FLIGHT_RECORDER") or None

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, name: str, **fields) -> None:
        if not self.active:
            return
        ev = {"ts": time.time(), "kind": kind, "name": name}
        if "trace" not in fields:
            # events inherit the ambient trace id so failover/spill/replay
            # records stitch into the corpus trace that triggered them
            ctx = _CTX.get()
            if ctx is not None:
                ev["trace"] = ctx[0]
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)

    @contextmanager
    def span(self, name: str, **fields):
        """Timed span; on any exit (including exception) the duration and
        outcome land in the ring.

        Spans participate in the trace context: an explicit ``trace=``
        field starts/continues that trace; otherwise the ambient context's
        trace id is inherited.  Either way the body runs under a fresh
        span id (with the previous span recorded as ``parent``), so
        nested spans — and RPC calls, which serialize the context into
        their request headers — chain into one stitched corpus trace.
        """
        if not self.active:
            yield
            return
        parent = _CTX.get()
        tid = fields.get("trace") or (parent[0] if parent else None)
        token = None
        if tid is not None:
            sid = new_span_id()
            fields["trace"] = tid
            fields["span"] = sid
            if parent is not None and parent[0] == tid:
                fields["parent"] = parent[1]
            token = _CTX.set((tid, sid))
        t0 = time.perf_counter()
        try:
            yield
        except BaseException as e:
            self.record(
                "span",
                name,
                dur_ms=round((time.perf_counter() - t0) * 1e3, 3),
                error=f"{type(e).__name__}: {e}",
                **fields,
            )
            raise
        finally:
            if token is not None:
                _CTX.reset(token)
        self.record(
            "span",
            name,
            dur_ms=round((time.perf_counter() - t0) * 1e3, 3),
            **fields,
        )

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
        self._dumped = False

    # -- crash dump --------------------------------------------------------

    def dump(self, path: str | None = None, *, reason: str = "") -> str | None:
        """Write the ring as JSONL (oldest first) to ``path`` (default: the
        configured dump path).  Returns the path written, or None when no
        destination is configured.  Uses plain ``open`` on purpose — see
        module docstring."""
        path = path or self.dump_path()
        if not path:
            return None
        events = self.snapshot()
        header = {
            "ts": time.time(),
            "kind": "dump",
            "name": "flight_recorder",
            "pid": os.getpid(),
            "reason": reason,
            "events": len(events),
        }
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(header) + "\n")
            for ev in events:
                fh.write(json.dumps(ev, default=str) + "\n")
            fh.flush()
            try:
                os.fsync(fh.fileno())
            except OSError:
                pass
        return path

    def dump_on_fault(self, reason: str) -> str | None:
        """Crash-path dump: records the fault event, writes the sidecar
        once (repeated faults in one death don't multiply dumps), and
        never raises — the crash in progress owns the control flow."""
        try:
            if not self.active:
                return None
            self.record("fault", "crash", reason=reason)
            with self._lock:
                if self._dumped:
                    return None
                self._dumped = True
            # fault hooks land their state (e.g. the stage-graph runtime's
            # whole-graph drain snapshot) in the ring BEFORE the dump —
            # each individually guarded so one bad hook cannot cost the
            # sidecar its remaining events
            for fn in list(_FAULT_HOOKS):
                try:
                    fn(self)
                except Exception:
                    pass
            return self.dump(reason=reason)
        except Exception:
            return None


RECORDER = FlightRecorder()

# module-level conveniences bound to the process recorder
span = RECORDER.span
record = RECORDER.record
dump = RECORDER.dump
dump_on_fault = RECORDER.dump_on_fault
set_dump_path = RECORDER.set_dump_path
dump_path = RECORDER.dump_path


def enabled() -> bool:
    return RECORDER.active


def set_enabled(on: bool | None) -> None:
    RECORDER.set_active(on)


def install_excepthook() -> None:
    """Chain the flight-recorder dump onto ``sys.excepthook`` so an
    uncaught exception (not just chaos faults) leaves a sidecar.  Long-
    running entry points (bench, CLI scrape) opt in; libraries never
    mutate the hook on import."""
    import sys

    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        dump_on_fault(f"uncaught {exc_type.__name__}: {exc}")
        prev(exc_type, exc, tb)

    sys.excepthook = hook
