"""Structured span tracing + in-memory crash flight recorder.

Metrics (``obs/telemetry.py``) answer "how fast, how many"; this module
answers "what exactly was in flight when it died".  A fixed-size ring
buffer records spans and events — batch/trace IDs flow from document
ingest through encode → H2D → kernel dispatch → resolve → matcher — and
on a crash or a chaos-injected fault the ring is dumped to a JSONL
sidecar, so PR 1's kill-restart harness (``tools/crashsweep.py``) can
assert on the recorder's last-known state instead of reconstructing it
from log lines.

Recording is OFF unless ``ASTPU_TELEMETRY`` is truthy or
``ASTPU_FLIGHT_RECORDER=<path>`` names a dump destination (the env knob
forked children inherit, mirroring ``ASTPU_CHAOS_FS``).  Disabled,
:func:`span` costs one attribute check before yielding.

The dump path deliberately bypasses the ``storage.fsio`` seam: the
recorder fires *during* injected storage faults, and routing its own
sidecar through the faulty substrate would recurse the injection (and
torn flight logs defeat their purpose).  Every record line is
self-contained JSON, so even a tail cut off by the OS stays parseable
line-by-line.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

__all__ = [
    "FlightRecorder",
    "RECORDER",
    "enabled",
    "set_enabled",
    "set_dump_path",
    "dump_path",
    "span",
    "record",
    "new_trace_id",
    "dump",
    "dump_on_fault",
    "add_fault_hook",
    "install_excepthook",
]

#: callbacks run (once, with the recorder) inside :meth:`dump_on_fault`
#: BEFORE the sidecar is written — how the stage-graph runtime lands a
#: whole-graph drain snapshot in the ring at the kill point without this
#: module importing the runtime (the hook is registered BY the runtime).
_FAULT_HOOKS: list = []


def add_fault_hook(fn) -> None:
    """Register ``fn(recorder)`` to run on the crash path.  Hooks must be
    fast and must never raise (they run while the process is dying)."""
    if fn not in _FAULT_HOOKS:
        _FAULT_HOOKS.append(fn)

_TRUTHY = ("1", "true", "yes", "on")

_trace_ids = itertools.count(1)


def new_trace_id() -> str:
    """Process-unique trace id (pid-qualified so multi-process sweeps can
    interleave their sidecars without collision)."""
    return f"{os.getpid():x}-{next(_trace_ids):x}"


class FlightRecorder:
    """Bounded ring of structured events; thread-safe; cheap when off."""

    def __init__(self, capacity: int = 2048):
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._active: bool | None = None  # None → resolve from env lazily
        self._dump_path: str | None = None
        self._dumped = False
        self.capacity = capacity

    # -- gating ------------------------------------------------------------

    @property
    def active(self) -> bool:
        if self._active is None:
            env = os.environ
            self._active = (
                env.get("ASTPU_TELEMETRY", "").lower() in _TRUTHY
                or bool(env.get("ASTPU_FLIGHT_RECORDER"))
            )
        return self._active

    def set_active(self, on: bool | None) -> None:
        self._active = on

    def set_dump_path(self, path: str | None) -> None:
        self._dump_path = path

    def dump_path(self) -> str | None:
        return self._dump_path or os.environ.get("ASTPU_FLIGHT_RECORDER") or None

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, name: str, **fields) -> None:
        if not self.active:
            return
        ev = {"ts": time.time(), "kind": kind, "name": name}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)

    @contextmanager
    def span(self, name: str, **fields):
        """Timed span; on any exit (including exception) the duration and
        outcome land in the ring.  ``trace``/``batch`` fields carry IDs
        across stages."""
        if not self.active:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        except BaseException as e:
            self.record(
                "span",
                name,
                dur_ms=round((time.perf_counter() - t0) * 1e3, 3),
                error=f"{type(e).__name__}: {e}",
                **fields,
            )
            raise
        self.record(
            "span",
            name,
            dur_ms=round((time.perf_counter() - t0) * 1e3, 3),
            **fields,
        )

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
        self._dumped = False

    # -- crash dump --------------------------------------------------------

    def dump(self, path: str | None = None, *, reason: str = "") -> str | None:
        """Write the ring as JSONL (oldest first) to ``path`` (default: the
        configured dump path).  Returns the path written, or None when no
        destination is configured.  Uses plain ``open`` on purpose — see
        module docstring."""
        path = path or self.dump_path()
        if not path:
            return None
        events = self.snapshot()
        header = {
            "ts": time.time(),
            "kind": "dump",
            "name": "flight_recorder",
            "pid": os.getpid(),
            "reason": reason,
            "events": len(events),
        }
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(header) + "\n")
            for ev in events:
                fh.write(json.dumps(ev, default=str) + "\n")
            fh.flush()
            try:
                os.fsync(fh.fileno())
            except OSError:
                pass
        return path

    def dump_on_fault(self, reason: str) -> str | None:
        """Crash-path dump: records the fault event, writes the sidecar
        once (repeated faults in one death don't multiply dumps), and
        never raises — the crash in progress owns the control flow."""
        try:
            if not self.active:
                return None
            self.record("fault", "crash", reason=reason)
            with self._lock:
                if self._dumped:
                    return None
                self._dumped = True
            # fault hooks land their state (e.g. the stage-graph runtime's
            # whole-graph drain snapshot) in the ring BEFORE the dump —
            # each individually guarded so one bad hook cannot cost the
            # sidecar its remaining events
            for fn in list(_FAULT_HOOKS):
                try:
                    fn(self)
                except Exception:
                    pass
            return self.dump(reason=reason)
        except Exception:
            return None


RECORDER = FlightRecorder()

# module-level conveniences bound to the process recorder
span = RECORDER.span
record = RECORDER.record
dump = RECORDER.dump
dump_on_fault = RECORDER.dump_on_fault
set_dump_path = RECORDER.set_dump_path
dump_path = RECORDER.dump_path


def enabled() -> bool:
    return RECORDER.active


def set_enabled(on: bool | None) -> None:
    RECORDER.set_active(on)


def install_excepthook() -> None:
    """Chain the flight-recorder dump onto ``sys.excepthook`` so an
    uncaught exception (not just chaos faults) leaves a sidecar.  Long-
    running entry points (bench, CLI scrape) opt in; libraries never
    mutate the hook on import."""
    import sys

    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        dump_on_fault(f"uncaught {exc_type.__name__}: {exc}")
        prev(exc_type, exc, tb)

    sys.excepthook = hook
