"""Decision-provenance plane: every dedup verdict names the tier that
settled it.

A duplicate verdict used to be an unexplainable bit: ``rep[i] != i`` (or
``attr[i] >= 0``) with no record of WHICH evidence settled it — the exact
memcmp stage, a persistent-index posting hit, a raw LSH band collision,
the rerank tier's device sketch, the margin band's exact Jaccard, or the
borderline ANN re-probe.  This module is the one place those verdicts
become observable:

- **always-on counters** — ``astpu_decision_total{tier, verdict}``
  (:data:`TIERS` × dup/unique), registered ONLY here (single-ownership,
  ``tools/lint_metrics.py``) and incremented through
  :class:`DecisionRecorder` by every producer (``pipeline/dedup.py``'s
  resolve paths, ``pipeline/rerank.py`` via the engine,
  ``extractors/tpu_batch.py``'s exact/bloom/persist stages).  Like the
  stage histograms, they bypass the telemetry gate: per-tier verdict
  accounting is the trust substrate a per-tenant quality SLO bills
  against, so it can never be dark.
- **a bounded, sampled JSONL journal** — one record per decision
  (doc id, tier, verdict, attributed doc, winning band key), appended
  through the ``storage/fsio`` seam so ChaosFs torn-tail faults are
  first-class tested.  Torn tails are tolerated by the reader (records
  drop whole, never corrupt — the ``lookup_names``/perf-ledger
  convention), "dup" records are always kept while "unique" records are
  sampled (``sample``), and the file rotates to ``<path>.old`` at
  ``max_bytes`` so the sidecar is bounded at 2× the cap.
  ``tools/explain_dedup.py`` joins these records against the persistent
  index's postings to answer "why is doc X a dup of Y".

The journal is OFF by default (``ASTPU_DECISION_JOURNAL=<path>``
enables; ``ASTPU_DECISION_SAMPLE`` / ``ASTPU_DECISION_JOURNAL_MAX_BYTES``
tune it).  Disabled, producers take a structural zero-overhead path:
``DecisionRecorder.journal is None`` gates every row-building branch, so
the only per-corpus cost is the counter increments (regression-tested
like the PR 3 telemetry gate).

Layering: this module is hook-injected — it imports ``obs.telemetry``
and the fsio seam only, never ``pipeline``/``index``/``extractors``
internals (enforced by ``tools/lint_imports.py``).  Producers call in;
nothing here calls out.
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = [
    "TIERS",
    "VERDICTS",
    "DecisionJournal",
    "DecisionRecorder",
    "get_recorder",
    "set_recorder",
    "configure",
    "decision_mix_snapshot",
    "decision_mix_delta",
]

#: the settling tiers, in evidence order (strongest first):
#: ``exact``   — byte/url-identity stage (memcmp-confirmed first-seen);
#: ``index``   — persistent/bloom stream-index posting hit;
#: ``band``    — raw LSH band collision settled by the signature
#:               estimator (or a collision-free unique);
#: ``rerank``  — the precision tier's device bottom-sketch settle or its
#:               precision-targeted eviction;
#: ``margin``  — host exact-Jaccard re-settle of the margin band (both
#:               the rerank margin and the certified path's
#:               exact_verify_band);
#: ``reprobe`` — the borderline ANN re-probe over index postings.
TIERS = ("exact", "index", "band", "rerank", "margin", "reprobe")
VERDICTS = ("dup", "unique")

JOURNAL_ENV = "ASTPU_DECISION_JOURNAL"
SAMPLE_ENV = "ASTPU_DECISION_SAMPLE"
MAX_BYTES_ENV = "ASTPU_DECISION_JOURNAL_MAX_BYTES"
DEFAULT_SAMPLE = 0.05
DEFAULT_MAX_BYTES = 64 << 20

_MIX = 2654435761  # Knuth multiplicative hash: seeded per-seq sampling


class DecisionJournal:
    """Bounded, sampled, torn-tail-tolerant JSONL decision sidecar.

    Append-only through the fsio seam; each :meth:`append` writes whole
    ``\\n``-terminated lines in one buffer, so a ChaosFs short write can
    only ever tear the LAST line — which the reader (and every torn-tail
    reader in the tree) drops whole.  After a failed append the next one
    leads with a ``\\n``: a record can never merge into a torn tail and
    parse as garbage.
    """

    def __init__(
        self,
        path: str,
        *,
        fs=None,
        sample: float = DEFAULT_SAMPLE,
        max_bytes: int = DEFAULT_MAX_BYTES,
        seed: int = 0,
    ):
        from advanced_scrapper_tpu.storage.fsio import default_fs

        self.path = path
        self._fs = fs or default_fs()
        self.sample = float(sample)
        self.max_bytes = int(max_bytes)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._seq = 0
        self._torn = False  # last append faulted: lead the next with \n
        self.appended = 0
        self.sampled_out = 0
        self.write_errors = 0

    def _keep(self, seq: int, verdict: str) -> bool:
        """dup records are always kept (they are what explain queries
        join on); unique records are sampled — deterministically per
        (seed, seq), not by a shared random stream, so a run's journal
        is reproducible."""
        if verdict != "unique" or self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        h = (((seq + self.seed) * _MIX) & 0xFFFFFFFF) / 2.0**32
        return h < self.sample

    def append(self, rows) -> int:
        """Append decision rows (dicts); returns the count actually
        journaled (after sampling).  OSErrors are contained: a faulty
        substrate costs records, never the producer."""
        ts = round(time.time(), 3)
        with self._lock:
            payload = []
            for row in rows:
                seq = self._seq
                self._seq += 1
                if not self._keep(seq, row.get("verdict", "")):
                    self.sampled_out += 1
                    continue
                rec = {"seq": seq, "ts": ts}
                rec.update(row)
                payload.append(
                    json.dumps(rec, separators=(",", ":"), sort_keys=True)
                )
            if not payload:
                return 0
            data = ("\n".join(payload) + "\n").encode("utf-8")
            if self._torn:
                data = b"\n" + data
            try:
                self._rotate_locked(len(data))
                with self._fs.open(self.path, "ab") as fh:
                    fh.write(data)
            except OSError:
                self.write_errors += 1
                self._torn = True
                from advanced_scrapper_tpu.obs import telemetry

                telemetry.event_counter(
                    "astpu_decision_journal_errors_total",
                    "decision-journal appends that faulted (records lost "
                    "whole; the journal stays parseable)",
                ).inc()
                return 0
            self._torn = False
            self.appended += len(payload)
            return len(payload)

    def _rotate_locked(self, incoming: int) -> None:
        """One-deep rotation at the byte cap: ``path`` → ``path.old``.
        The sidecar is bounded at ~2× ``max_bytes``; readers walk both
        generations oldest-first."""
        if self.max_bytes <= 0:
            return
        try:
            size = self._fs.size(self.path) if self._fs.exists(self.path) else 0
            if size + incoming <= self.max_bytes:
                return
            old = self.path + ".old"
            if self._fs.exists(old):
                self._fs.remove(old)
            self._fs.replace(self.path, old)
        except OSError:
            pass  # rotation is best-effort; append decides durability

    @staticmethod
    def read(path: str, fs=None) -> list[dict]:
        """Every parseable record, ``path.old`` first (oldest-first).
        An unterminated tail is torn — dropped whole; a line that fails
        to parse (merged torn garbage, bit rot) is skipped, never
        propagated."""
        from advanced_scrapper_tpu.storage.fsio import default_fs

        fs = fs or default_fs()
        out: list[dict] = []
        for p in (path + ".old", path):
            if not fs.exists(p):
                continue
            with fs.open(p, "rb") as fh:
                data = fh.read()
            for line in data.split(b"\n")[:-1]:  # unterminated tail = torn
                if not line:
                    continue
                try:
                    rec = json.loads(line.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
        return out


class DecisionRecorder:
    """The producer handle: always-on per-(tier, verdict) counters plus
    the optional journal.  Producers gate every row-building branch on
    ``recorder.journal is not None`` — the disabled journal costs
    nothing but the counter increments."""

    def __init__(self, journal: DecisionJournal | None = None, registry=None):
        from advanced_scrapper_tpu.obs import telemetry

        self._reg = registry or telemetry.REGISTRY
        self.journal = journal
        self._handles: dict[tuple[str, str], object] = {}
        self._gen = self._reg.generation
        self._hlock = threading.Lock()

    def _handle(self, tier: str, verdict: str):
        # the admission plane's lazy re-instrument pattern: a registry
        # reset (tests) bumps `generation`; cached handles from the old
        # generation would increment outside the fresh registry's view
        with self._hlock:
            if self._gen != self._reg.generation:
                self._handles.clear()
                self._gen = self._reg.generation
            key = (tier, verdict)
            h = self._handles.get(key)
            if h is None:
                h = self._reg.counter(
                    "astpu_decision_total",
                    "dedup verdicts by the tier that settled them "
                    "(always-on decision provenance)",
                    always=True,
                    tier=tier,
                    verdict=verdict,
                )
                self._handles[key] = h
            return h

    def count(self, tier: str, verdict: str, n: int = 1) -> None:
        if n:
            self._handle(tier, verdict).inc(n)

    def journal_rows(self, rows) -> int:
        j = self.journal
        return j.append(rows) if j is not None else 0

    def record(self, tier: str, verdict: str, **fields) -> None:
        """Count + journal ONE decision — for sparse call sites (the
        batch paths build row lists and call :meth:`journal_rows`)."""
        self.count(tier, verdict)
        if self.journal is not None:
            self.journal.append([{"tier": tier, "verdict": verdict, **fields}])


_LOCK = threading.Lock()
_RECORDER: DecisionRecorder | None = None


def get_recorder() -> DecisionRecorder:
    """The process-wide recorder, built lazily from the env knobs
    (``ASTPU_DECISION_JOURNAL`` path — empty/unset disables the
    journal)."""
    global _RECORDER
    with _LOCK:
        if _RECORDER is None:
            path = os.environ.get(JOURNAL_ENV, "")
            journal = None
            if path:
                journal = DecisionJournal(
                    path,
                    sample=float(
                        os.environ.get(SAMPLE_ENV, "") or DEFAULT_SAMPLE
                    ),
                    max_bytes=int(
                        os.environ.get(MAX_BYTES_ENV, "") or DEFAULT_MAX_BYTES
                    ),
                )
            _RECORDER = DecisionRecorder(journal)
        return _RECORDER


def set_recorder(recorder: DecisionRecorder | None) -> None:
    """Install (or clear — next :func:`get_recorder` re-reads the env)
    the process recorder; tests and tools wire explicit journals here."""
    global _RECORDER
    with _LOCK:
        _RECORDER = recorder


def configure(
    journal_path: str | None,
    *,
    sample: float = DEFAULT_SAMPLE,
    max_bytes: int = DEFAULT_MAX_BYTES,
    fs=None,
    seed: int = 0,
) -> DecisionRecorder:
    """Build + install a recorder explicitly (None/'' path = counters
    only).  Returns the installed recorder."""
    journal = None
    if journal_path:
        journal = DecisionJournal(
            journal_path, fs=fs, sample=sample, max_bytes=max_bytes, seed=seed
        )
    rec = DecisionRecorder(journal)
    set_recorder(rec)
    return rec


def decision_mix_snapshot(registry=None) -> dict[str, float]:
    """``{"<tier>:<verdict>": count}`` from the live counters — the
    snapshot/delta surface bench's per-regime ``<regime>_decision_mix``
    keys subtract over (the ``regime_device_counters`` pattern)."""
    from advanced_scrapper_tpu.obs import telemetry

    reg = registry or telemetry.REGISTRY
    out: dict[str, float] = {}
    for m in reg.find("astpu_decision_total"):
        tier = m.labels.get("tier", "?")
        verdict = m.labels.get("verdict", "?")
        out[f"{tier}:{verdict}"] = float(m.value)
    return out


def decision_mix_delta(
    before: dict[str, float], after: dict[str, float] | None = None
) -> dict[str, float]:
    """Non-zero per-(tier, verdict) deltas since ``before``."""
    if after is None:
        after = decision_mix_snapshot()
    out = {}
    for k, v in sorted(after.items()):
        d = v - before.get(k, 0.0)
        if d:
            out[k] = d
    return out
