"""Console multiplexer: scrolling event lines above a live status line.

Successor of the reference's print thread + ANSI dance
(``constant_rate_scrapper.py:26,106-112,257-287``): one consumer thread
drains a queue of ``(message, is_stats_line)`` tuples; stats lines overwrite
in place with ``\\r``/``\\033[K`` while event lines scroll above and the
stats line is repainted beneath them.  Single-writer by construction — the
reference's unlocked global ``print_queue`` is a constructor-injected queue
here (SURVEY.md §5.2).
"""

from __future__ import annotations

import queue
import sys
import threading

GREEN = "\033[92m"
RED = "\033[91m"
RESET = "\033[00m"


def green(msg: str) -> str:
    return f"{GREEN}{msg}{RESET}"


def red(msg: str) -> str:
    return f"{RED}{msg}{RESET}"


class ConsoleMux:
    def __init__(self, out=None):
        self._out = out if out is not None else sys.stdout
        self._queue: "queue.Queue[tuple[str, bool]]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_stats_line = ""

    # -- producers ---------------------------------------------------------

    def event(self, message: str) -> None:
        self._queue.put((message, False))

    def success(self, message: str) -> None:
        self.event(green(message))

    def failure(self, message: str) -> None:
        self.event(red(message))

    def stats(self, line: str) -> None:
        self._queue.put((line, True))

    # -- consumer ----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None

    def start(self) -> "ConsoleMux":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def drain(self) -> None:
        """Render everything queued so far (synchronous, for tests/shutdown).
        No-op while a consumer thread is running — it owns the queue."""
        if self.running:
            return
        while True:
            try:
                message, is_stats = self._queue.get_nowait()
            except queue.Empty:
                return
            self._render(message, is_stats)

    def _run(self) -> None:
        while not self._stop.is_set() or not self._queue.empty():
            try:
                message, is_stats = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            self._render(message, is_stats)

    def _render(self, message: str, is_stats: bool) -> None:
        w = self._out.write
        if is_stats:
            w("\r\033[K" + message)
            self._last_stats_line = message
        elif self._last_stats_line:
            w("\r\033[K" + message + "\n" + self._last_stats_line)
        else:
            w(message + "\n")
        self._out.flush()
