"""Platform-aware bench-history engine: trajectories, not one-shot numbers.

Five ``BENCH_*.json`` rounds, five ``MULTICHIP_*.json`` dryruns and a soak
record are checked into the repo root, and until now nothing READ them:
every bench run printed a JSON line into the void, and BENCH_r05's silent
cpu-fallback cost a full diagnosis cycle because nothing flagged that its
numbers were being eyeballed against an on-chip round.  This module is
the append-only ledger + comparison engine behind ``tools/perf_ledger.py``
and bench's end-of-run history verdict:

- **rows** — one flat dict per measurement run: a *platform key* (from
  the PR 15 ``platform_fingerprint`` when present, the legacy
  ``platform`` field otherwise, ``"unlabeled"`` for the pre-r03 rounds
  that predate the stamp), a source name, an ordering hint (the ``rNN``
  round number when the filename carries one, else the ingest
  timestamp), the git sha, and every numeric metric flattened to dotted
  keys (``stage_ms.encode``);
- **trajectories** — per-(platform, metric) ordered value series;
- **verdicts** — regression/improvement/stable per metric, comparing the
  last row against its predecessor **on the same platform only**: a
  cpu-fallback round is never judged against an on-chip one (the exact
  comparison that burned PR 9), and rows whose platform key appears once
  produce trajectory but no verdict.  Metric direction is resolved by
  name (rates/recalls up = better, latencies/skews/compiles down =
  better; unknown shapes get a trajectory but no verdict — a silent
  wrong-direction verdict is worse than none);
- **ledger** — an append-only JSONL file (``ASTPU_PERF_LEDGER`` names
  it for bench; ``tools/sweep_onchip.py`` appends every sweep point) so
  the history survives outside the checked-in artifacts.

Everything here is stdlib-only and jax-free: the sweep parent (which
must never import jax — a dead tunnel hangs backend imports) ingests
through this module directly.
"""

from __future__ import annotations

import json
import math
import os
import re
import subprocess
import time

__all__ = [
    "PerfLedger",
    "platform_key",
    "metric_direction",
    "flatten_metrics",
    "row_from_result",
    "rows_from_artifact",
    "scan_repo_artifacts",
    "row_from_canary_sli",
    "trajectories",
    "compute_verdicts",
    "build_report",
    "report_markdown",
    "bench_history_verdict",
    "git_sha",
    "parse_source_knobs",
    "best_knob_profile",
]

SCHEMA = 1
DEFAULT_THRESHOLD = 0.10  # |relative change| above this → a verdict moves

_ROUND_RE = re.compile(r"_r(\d+)\b")
#: top-level result keys that are structure, not metrics
_SKIP_KEYS = {
    "telemetry", "perf_history", "platform_fingerprint", "metric", "unit",
    "platform", "regime", "sharded_per_shard", "sharded_mesh", "config",
    "status",
}

_HIGHER = (
    "_per_sec", "_per_s", "_rows_per_sec", "_urls_per_sec",
    "_vs_baseline", "_vs_pandas", "_caught",
)
_LOWER = ("_ms", "_s", "_seconds", "_skew", "_compiles", "_bytes")
_HIGHER_EXACT = {"value", "vs_baseline", "docs_per_s", "articles_per_s"}
_HIGHER_PREFIX = ("recall", "precision", "vpu_util")
_LOWER_EXACT = {"unchained_merges", "false_drops", "measured_fp", "compile_s"}


def _segment_direction(seg: str) -> int:
    if seg in _HIGHER_EXACT or seg.startswith(_HIGHER_PREFIX):
        return 1
    if seg in _LOWER_EXACT:
        return -1
    for suf in _HIGHER:
        if seg.endswith(suf):
            return 1
    for suf in _LOWER:
        if seg.endswith(suf):
            return -1
    return 0


def metric_direction(name: str) -> int:
    """``+1`` higher-is-better, ``-1`` lower-is-better, ``0`` unknown (a
    trajectory is still kept; no verdict is issued — wrong-direction
    verdicts are worse than silence).  Resolved leaf-first, then up the
    dotted path, so ``stage_ms.encode`` inherits the ``_ms`` suffix its
    PARENT key carries (the leaf alone says nothing)."""
    for seg in reversed(name.split(".")):
        d = _segment_direction(seg)
        if d:
            return d
    return 0


def platform_key(result: dict) -> str:
    """The partition key same-platform comparison runs under.  A PR 15
    ``platform_fingerprint`` wins (``backend/device_kindxN`` — two
    tunnels with different chip counts never compare); the legacy
    ``platform`` string is next; rows predating both are ``unlabeled``
    and only ever compare among themselves."""
    fp = result.get("platform_fingerprint")
    if isinstance(fp, dict) and fp.get("backend"):
        kind = str(fp.get("device_kind", "?")).replace(" ", "-")
        return f"{fp['backend']}/{kind}x{fp.get('device_count', '?')}"
    p = result.get("platform")
    return str(p) if p else "unlabeled"


def flatten_metrics(result: dict, prefix: str = "") -> dict[str, float]:
    """Every numeric scalar in a result dict, dotted-flattened; bools,
    strings, lists and the structural keys (telemetry ledger, fingerprint)
    are skipped."""
    out: dict[str, float] = {}
    for k, v in result.items():
        if not prefix and k in _SKIP_KEYS:
            continue
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            if isinstance(v, float) and not math.isfinite(v):
                continue
            out[key] = float(v)
        elif isinstance(v, dict):
            out.update(flatten_metrics(v, prefix=f"{key}."))
    return out


def git_sha(repo_dir: str | None = None) -> str:
    """Short HEAD sha of ``repo_dir`` (best-effort; ``"unknown"`` when
    git is absent or the dir is not a checkout)."""
    try:
        return (
            subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                cwd=repo_dir or os.getcwd(),
                capture_output=True,
                text=True,
                timeout=10,
            ).stdout.strip()
            or "unknown"
        )
    except Exception:
        return "unknown"


def _round_order(source: str) -> float | None:
    """Ordering hint from an ``_rNN`` round tag in the source name —
    artifact rounds sort by round number; everything else returns
    ``None`` (sorted after every round, by timestamp — see
    ``_row_sort_key``).  ``None``, NOT ``math.inf``: rows are JSONL and
    ``json.dumps(inf)`` emits the non-standard ``Infinity`` token that
    breaks every strict parser reading the documented ledger format."""
    m = _ROUND_RE.search(source)
    return float(m.group(1)) if m else None


def row_from_result(
    result: dict,
    *,
    source: str,
    kind: str = "bench",
    ts: float | None = None,
    platform: str | None = None,
    git: str | None = None,
) -> dict:
    """One ledger row from a result dict (a bench JSON line, a sweep
    point, an artifact's parsed payload)."""
    fp = result.get("platform_fingerprint")
    return {
        "schema": SCHEMA,
        "kind": kind,
        "source": source,
        "order": _round_order(source),
        "ts": time.time() if ts is None else ts,
        "platform": platform or platform_key(result),
        "fingerprint": fp if isinstance(fp, dict) else None,
        "git_sha": git
        or (fp or {}).get("git_sha")
        or result.get("git_sha")
        or "",
        "metrics": flatten_metrics(result),
    }


def row_from_canary_sli(
    sli: dict,
    *,
    platform: str,
    source: str = "canary",
    ts: float | None = None,
    git: str = "",
) -> dict:
    """One ledger row from a canary prober SLI dict
    (``obs/canary.py``'s ``run_round`` result) — live quality joins the
    same platform-partitioned trajectory engine as bench throughput, so
    a recall slide across rounds shows up in ``tools/perf_ledger.py``
    like any perf regression.  The ``recall``/``precision`` keys carry
    their higher-is-better direction by prefix and
    ``canary_latency_seconds`` its lower-is-better by suffix; the shape
    counters (``oracle_pairs``…) keep trajectories but draw no verdict.
    """
    metrics = {
        f"canary_{k}": float(v)
        for k, v in sli.items()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
        and (not isinstance(v, float) or math.isfinite(v))
    }
    # leaf-first direction resolution reads the prefix off the leaf:
    # strip the canary_ prefix from the quality SLIs so they land in the
    # recall/precision higher-is-better family
    for k in ("recall", "precision"):
        if f"canary_{k}" in metrics:
            metrics[k] = metrics.pop(f"canary_{k}")
    return {
        "schema": SCHEMA,
        "kind": "canary",
        "source": source,
        "order": None,
        "ts": time.time() if ts is None else ts,
        "platform": platform,
        "fingerprint": None,
        "git_sha": git,
        "metrics": metrics,
    }


# -- checked-in artifact ingestion -------------------------------------------


def _multichip_payload(raw: dict) -> dict | None:
    """The ``MULTICHIP {...}`` JSON line from a dryrun record's tail."""
    tail = raw.get("tail") or ""
    for line in tail.splitlines():
        line = line.strip()
        if line.startswith("MULTICHIP "):
            try:
                return json.loads(line[len("MULTICHIP "):])
            except ValueError:
                return None
    return None


def rows_from_artifact(path: str) -> list[dict]:
    """Ledger rows from one checked-in artifact (``BENCH_*.json``,
    ``MULTICHIP_*.json``, ``SOAK_*.json``).  Driver wrappers (``parsed``
    payloads, MULTICHIP tails) are unwrapped; a failed round (non-zero
    rc, no payload) yields no rows — absence IS the honest record."""
    name = os.path.basename(path)
    try:
        with open(path, encoding="utf-8") as fh:
            raw = json.load(fh)
    except (OSError, ValueError):
        return []
    if not isinstance(raw, dict):
        return []
    if name.startswith("BENCH"):
        payload = raw.get("parsed") if isinstance(raw.get("parsed"), dict) else (
            raw if "metric" in raw else None
        )
        if not payload:
            return []
        return [row_from_result(payload, source=name, kind="bench_round", ts=0.0)]
    if name.startswith("MULTICHIP"):
        payload = _multichip_payload(raw)
        if not payload or not raw.get("ok", False):
            return []
        metrics: dict = {}
        for entry in payload.get("scaling", ()):
            d = entry.get("devices")
            if d is None:
                continue
            for mk, rk in (
                ("articles_per_s", f"multichip_d{d}_articles_per_s"),
                ("compile_s", f"multichip_d{d}_compile_s"),
                ("step_ms", f"multichip_d{d}_step_ms"),
            ):
                if isinstance(entry.get(mk), (int, float)):
                    metrics[rk] = float(entry[mk])
        if not metrics:
            return []
        # dryrun platform: the driver's device count is the only stamp
        # these records carry — partitioned apart from every bench round
        plat = f"multichip-{raw.get('n_devices', '?')}dev"
        return [
            {
                "schema": SCHEMA,
                "kind": "multichip_round",
                "source": name,
                "order": _round_order(name),
                "ts": 0.0,
                "platform": plat,
                "fingerprint": None,
                "git_sha": "",
                "metrics": metrics,
            }
        ]
    if name.startswith("SOAK"):
        if not flatten_metrics(raw):
            return []
        return [
            row_from_result(
                raw,
                source=name,
                kind="soak_round",
                ts=0.0,
                platform=f"soak/{raw.get('platform') or 'unlabeled'}",
            )
        ]
    return []


def scan_repo_artifacts(repo_dir: str) -> list[dict]:
    """Every checked-in round artifact in ``repo_dir``, as ledger rows
    ordered by round."""
    rows: list[dict] = []
    try:
        names = sorted(os.listdir(repo_dir))
    except OSError:
        return rows
    for fn in names:
        if fn.endswith(".json") and fn.split("_")[0] in (
            "BENCH", "MULTICHIP", "SOAK"
        ):
            rows.extend(rows_from_artifact(os.path.join(repo_dir, fn)))
    rows.sort(key=_row_sort_key)
    return rows


def _row_sort_key(row: dict):
    order = row.get("order")
    if order is None:
        order = math.inf
    return (order, row.get("ts") or 0.0, row.get("source") or "")


# -- the ledger ---------------------------------------------------------------


class PerfLedger:
    """Append-only JSONL ledger of measurement rows.

    Torn-tail tolerant on read (a half-written last line is skipped, the
    WAL convention every reader in this tree follows); appends are one
    ``write`` + ``flush`` of a single line, so concurrent appenders from
    watchdogged sweep subprocesses interleave whole lines on POSIX.
    """

    def __init__(self, path: str):
        self.path = path

    def append(self, row: dict) -> None:
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        with open(self.path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(row, sort_keys=True) + "\n")
            fh.flush()

    def rows(self) -> list[dict]:
        out: list[dict] = []
        try:
            fh = open(self.path, encoding="utf-8")
        except OSError:
            return out
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue  # torn tail / foreign line: skip, never raise
                if isinstance(row, dict) and row.get("metrics"):
                    out.append(row)
        return out

    def sources(self) -> set[str]:
        return {r.get("source", "") for r in self.rows()}

    def ingest_result(self, result: dict, **kw) -> dict:
        row = row_from_result(result, **kw)
        self.append(row)
        return row

    def ingest_canary_sli(self, sli: dict, *, platform: str, **kw) -> dict:
        """Append one live-quality row (``row_from_canary_sli``); a
        canary scheduler points here so every probe round grows the
        same trajectory the bench rounds live in."""
        row = row_from_canary_sli(sli, platform=platform, **kw)
        self.append(row)
        return row

    def ingest_artifacts(self, paths) -> int:
        """Append rows for artifacts not yet in the ledger (deduped by
        source name); returns how many rows landed."""
        seen = self.sources()
        n = 0
        for p in paths:
            for row in rows_from_artifact(p):
                if row["source"] in seen:
                    continue
                self.append(row)
                seen.add(row["source"])
                n += 1
        return n


# -- trajectories + verdicts --------------------------------------------------


def trajectories(rows) -> dict[str, dict[str, list]]:
    """``{platform: {metric: [(source, value), ...]}}`` — the ordered
    per-platform series every verdict and report reads from."""
    rows = sorted(rows, key=_row_sort_key)
    out: dict[str, dict[str, list]] = {}
    for row in rows:
        plat = row.get("platform") or "unlabeled"
        per = out.setdefault(plat, {})
        for metric, v in (row.get("metrics") or {}).items():
            per.setdefault(metric, []).append((row.get("source", ""), v))
    return out


def compute_verdicts(
    rows, *, threshold: float = DEFAULT_THRESHOLD
) -> list[dict]:
    """Last-vs-previous verdict per (platform, metric) — SAME platform
    only, direction-aware, ``stable`` inside ±``threshold``.  Metrics
    with unknown direction or a single same-platform point yield no
    verdict (their trajectory still prints)."""
    verdicts: list[dict] = []
    for plat, series in sorted(trajectories(rows).items()):
        for metric, pts in sorted(series.items()):
            if len(pts) < 2:
                continue
            direction = metric_direction(metric)
            if direction == 0:
                continue
            (prev_src, prev), (last_src, last) = pts[-2], pts[-1]
            if prev == 0:
                continue
            change = (last - prev) / abs(prev)
            if abs(change) <= threshold:
                verdict = "stable"
            elif (change > 0) == (direction > 0):
                verdict = "improvement"
            else:
                verdict = "regression"
            verdicts.append(
                {
                    "platform": plat,
                    "metric": metric,
                    "prev": prev,
                    "prev_source": prev_src,
                    "last": last,
                    "last_source": last_src,
                    "change": round(change, 4),
                    "direction": "higher" if direction > 0 else "lower",
                    "verdict": verdict,
                }
            )
    return verdicts


def build_report(
    rows, *, threshold: float = DEFAULT_THRESHOLD
) -> dict:
    """The machine-readable report: platform-partitioned trajectories +
    verdicts + a one-glance summary."""
    rows = list(rows)
    traj = trajectories(rows)
    verdicts = compute_verdicts(rows, threshold=threshold)
    by_kind = {"regression": 0, "improvement": 0, "stable": 0}
    for v in verdicts:
        by_kind[v["verdict"]] += 1
    return {
        "rows": len(rows),
        "platforms": {
            plat: {
                "metrics": len(series),
                "points": sum(len(p) for p in series.values()),
            }
            for plat, series in sorted(traj.items())
        },
        "trajectories": {
            plat: {m: pts for m, pts in sorted(series.items())}
            for plat, series in sorted(traj.items())
        },
        "verdicts": verdicts,
        "summary": by_kind,
        "threshold": threshold,
    }


def _fmt_num(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.4g}"


def report_markdown(report: dict, *, max_points: int = 8) -> str:
    """The human half of the report: per-platform verdict tables plus
    compact trajectories (last ``max_points`` points per metric)."""
    lines = ["# Performance trajectory report", ""]
    s = report["summary"]
    lines.append(
        f"{report['rows']} rows across {len(report['platforms'])} "
        f"platform partitions — {s['regression']} regressions, "
        f"{s['improvement']} improvements, {s['stable']} stable "
        f"(threshold ±{report['threshold']:.0%}; same-platform "
        "comparisons only)."
    )
    lines.append("")
    verdicts_by_plat: dict[str, list] = {}
    for v in report["verdicts"]:
        verdicts_by_plat.setdefault(v["platform"], []).append(v)
    for plat, series in report["trajectories"].items():
        lines.append(f"## {plat}")
        lines.append("")
        vs = verdicts_by_plat.get(plat, [])
        moved = [v for v in vs if v["verdict"] != "stable"]
        if moved:
            lines.append("| metric | prev | last | change | verdict |")
            lines.append("|---|---|---|---|---|")
            for v in sorted(
                moved, key=lambda x: (x["verdict"], -abs(x["change"]))
            ):
                lines.append(
                    f"| {v['metric']} | {_fmt_num(v['prev'])} "
                    f"({v['prev_source']}) | {_fmt_num(v['last'])} "
                    f"({v['last_source']}) | {v['change']:+.1%} "
                    f"| **{v['verdict']}** |"
                )
        else:
            n_v = len(vs)
            lines.append(
                f"_no movement beyond ±{report['threshold']:.0%} "
                f"({n_v} comparable metrics)_"
                if n_v
                else "_single round — trajectory only, no comparison_"
            )
        lines.append("")
        for metric, pts in series.items():
            tail = pts[-max_points:]
            path = " → ".join(_fmt_num(v) for _s, v in tail)
            lines.append(f"- `{metric}`: {path}")
        lines.append("")
    return "\n".join(lines)


# -- per-platform knob profiles -----------------------------------------------

#: sweep-tag knob key → DedupConfig field name.  Only keys listed here
#: ever flow back into an engine config — a sweep tag's corpus-shape
#: keys (``n=…``) are the sweep's business, not dispatch knobs.
KNOB_FIELDS = {
    "put_workers": "put_workers",
    "window": "dispatch_window",
    "tile_rows": "rerank_tile_rows",
}


def parse_source_knobs(source: str) -> dict[str, int]:
    """Dispatch knobs encoded in a sweep row's source tag
    (``sweep:rerank:n=2048,put_workers=2,window=4,tile_rows=512``) as
    ``{config_field: value}``.  Unknown keys and non-integer values are
    skipped — the tag is free-form by design."""
    out: dict[str, int] = {}
    tail = source.rsplit(":", 1)[-1]
    for part in tail.split(","):
        k, sep, v = part.partition("=")
        field = KNOB_FIELDS.get(k.strip())
        if not sep or field is None:
            continue
        try:
            out[field] = int(v)
        except ValueError:
            continue
    return out


def best_knob_profile(path: str, platform_token: str) -> dict[str, int]:
    """Dispatch knobs from the ledger's best same-platform sweep row.

    Scans ``path`` for ``kind == "sweep"`` rows whose platform partition
    starts with ``platform_token`` (sweep rows stamp
    ``f"{backend}/swept-xN"``, so the bare jax backend name matches),
    takes the row with the highest ``*_articles_per_sec`` metric, and
    returns the knobs its source tag encodes.  Empty dict when the
    ledger has no matching row — callers fall back to their defaults.
    """
    best_rate, best_knobs = -1.0, {}
    for row in PerfLedger(path).rows():
        if row.get("kind") != "sweep":
            continue
        plat = str(row.get("platform") or "")
        if not plat.startswith(platform_token):
            continue
        rate = max(
            (
                v
                for k, v in (row.get("metrics") or {}).items()
                if k.endswith("_articles_per_sec")
                and isinstance(v, (int, float))
            ),
            default=None,
        )
        if rate is None or rate <= best_rate:
            continue
        knobs = parse_source_knobs(str(row.get("source") or ""))
        if knobs:
            best_rate, best_knobs = float(rate), knobs
    return best_knobs


# -- bench integration --------------------------------------------------------


def bench_history_verdict(
    result: dict,
    *,
    repo_dir: str,
    ledger_path: str | None = None,
    threshold: float = DEFAULT_THRESHOLD,
) -> dict:
    """Judge a just-finished bench result against the history (checked-in
    artifacts + optional ledger), SAME platform only — what bench folds
    into its end-of-run verdict.  Returns ``{platform, compared_against,
    verdicts, regressions, improvements}``; an empty ``compared_against``
    means no same-platform history exists (first on-chip round, fresh
    checkout) and no verdict is fabricated."""
    history = scan_repo_artifacts(repo_dir)
    if ledger_path:
        seen = {r.get("source") for r in history}
        for row in PerfLedger(ledger_path).rows():
            if row.get("source") not in seen:
                history.append(row)
    me = row_from_result(result, source="this-run")
    same = [r for r in history if r.get("platform") == me["platform"]]
    if not same:
        return {
            "platform": me["platform"],
            "compared_against": None,
            "verdicts": [],
            "regressions": 0,
            "improvements": 0,
        }
    prev = sorted(same, key=_row_sort_key)[-1]
    verdicts = [
        v
        for v in compute_verdicts([prev, me], threshold=threshold)
        if v["verdict"] != "stable"
    ]
    return {
        "platform": me["platform"],
        "compared_against": prev.get("source"),
        "verdicts": verdicts,
        "regressions": sum(1 for v in verdicts if v["verdict"] == "regression"),
        "improvements": sum(
            1 for v in verdicts if v["verdict"] == "improvement"
        ),
    }
