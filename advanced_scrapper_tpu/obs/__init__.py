from advanced_scrapper_tpu.obs.stats import StatsTracker
from advanced_scrapper_tpu.obs.console import ConsoleMux, green, red

__all__ = ["StatsTracker", "ConsoleMux", "green", "red"]
