from advanced_scrapper_tpu.obs.stats import StatsTracker
from advanced_scrapper_tpu.obs.console import ConsoleMux, green, red
from advanced_scrapper_tpu.obs import collector, slo, telemetry, trace

__all__ = [
    "StatsTracker",
    "ConsoleMux",
    "green",
    "red",
    "collector",
    "slo",
    "telemetry",
    "trace",
]
