"""Ground-truth canary prober: live recall/precision SLIs for dedup.

Quality was only ever measured offline (the 5-seed suites); the live
fleet's verdicts had no ground truth to compare against, so a silent
quality regression — a brownout stuck on, an index losing postings, a
mis-tuned knob profile — was invisible until the next offline run.
:class:`CanaryProber` closes that: it *generates* seeded synthetic
near-dup families with oracle answers measured by exact shingle Jaccard
(the suite's own truth definition), pushes them through the LIVE
resolution path, and scores the verdicts:

- ``astpu_canary_recall`` / ``astpu_canary_precision`` — pair-level
  SLIs of the last round (always-on gauges, registered ONLY here);
- ``astpu_canary_latency_seconds`` — end-to-end round latency
  (generate → resolve → settle), the user-visible quality-probe cost;
- ``astpu_canary_rounds_total`` / ``astpu_canary_postings_wiped_total``
  — probe cadence and the expiry proof-of-work.

The prober is **hook-injected**: it imports no ``pipeline``/``index``
internals (``tools/lint_imports.py`` enforces it) — the caller hands in
a ``resolve`` callable (the engine's certified one-shot, so the probe
exercises the real rerank/margin/band tiers and *feels* degradation-
ladder brownouts), and optionally an ``index_run`` + ``wipe`` pair bound
to a fleet client over a reserved ``canary:``-prefixed key space
(:data:`CANARY_SPACE_PREFIX` — the index layer declares the same
literal).  Canary postings live only inside that namespace and
:meth:`run_round` expires them via ``wipe`` before returning: real key
spaces never see a synthetic posting.

Declared objectives (:meth:`objectives`) plug the SLIs into the PR 11
SLO engine as ``gauge_min`` objectives with burn rates — a round whose
recall drops under ``recall_min`` (e.g. ``skip_rerank`` forced on)
flips ``astpu_slo_compliant{objective="canary_recall"}`` to violated,
and recovery flips it back.  The FleetCollector scrapes all of it
fleet-wide like any other series.
"""

from __future__ import annotations

import threading
import time

import numpy as np

__all__ = [
    "CANARY_SPACE_PREFIX",
    "CanaryProber",
    "make_canary_corpus",
]

#: reserved key-space name prefix for canary postings.  Duplicated (as a
#: literal) in ``index/remote.py``, which auto-provisions spaces under it
#: and restricts the ``wipe`` RPC to it — this module may not import the
#: index layer to share the constant.
CANARY_SPACE_PREFIX = "canary:"

_MIX = 2654435761


def _perturb(rng, tokens: list[str], n_swap: int, vocab: int) -> list[str]:
    """Replace ``n_swap`` distinct token positions with fresh vocabulary
    — the family-member generator (token swaps map ~linearly onto char-
    shingle Jaccard loss, and the oracle measures the truth anyway)."""
    out = list(tokens)
    for p in rng.choice(len(out), size=min(n_swap, len(out)), replace=False):
        out[int(p)] = f"x{int(rng.integers(vocab, 4 * vocab))}"
    return out


def make_canary_corpus(
    seed: int,
    *,
    families: int = 6,
    members: int = 4,
    distractors: int = 8,
    tokens: int = 60,
    vocab: int = 50_000,
    shingle_k: int = 8,
    threshold: float = 0.7,
):
    """Deterministic synthetic corpus with a measured oracle.

    Families alternate two regimes: **clear** (few token swaps, true
    J ≈ 0.85–0.95 — every tier catches these) and **knee** (swaps tuned
    so true J sits just above ``threshold`` — the estimator-fragile band
    whose recall the rerank/margin tiers exist to save; a brownout that
    skips them shows up HERE first).  Distractors are unrelated docs.

    Returns ``(texts, oracle)`` where ``oracle`` is the set of doc-index
    pairs ``(i, j), i < j`` whose EXACT shingle Jaccard (the oracle's own
    ``shingle_set``/``jaccard`` definition, imported so the two can never
    diverge) is ≥ ``threshold`` — ground truth by measurement, not by
    intent, so a swap that overshot never mislabels the oracle.
    """
    from advanced_scrapper_tpu.cpu.oracle import jaccard, shingle_set

    rng = np.random.default_rng((seed * _MIX) & 0xFFFFFFFF)
    texts: list[str] = []
    for f in range(families):
        base = [f"w{int(t)}" for t in rng.integers(0, vocab, size=tokens)]
        texts.append(" ".join(base))
        knee = f % 2 == 1
        for _m in range(members - 1):
            # knee members walk the swap count up until the measured J
            # falls into the target band (never below threshold: a
            # member that dropped out of the family would thin the
            # oracle, not stress the knee)
            n_swap = int(rng.integers(8, 13)) if knee else int(rng.integers(1, 3))
            cand = _perturb(rng, base, n_swap, vocab)
            if knee:
                a = shingle_set(" ".join(base).encode(), shingle_k)
                while (
                    n_swap > 0
                    and jaccard(
                        a, shingle_set(" ".join(cand).encode(), shingle_k)
                    )
                    < threshold + 0.02
                ):
                    n_swap -= 1
                    cand = _perturb(rng, base, n_swap, vocab)
            texts.append(" ".join(cand))
    for _d in range(distractors):
        texts.append(
            " ".join(
                f"w{int(t)}" for t in rng.integers(0, vocab, size=tokens)
            )
        )
    order = rng.permutation(len(texts))
    texts = [texts[int(i)] for i in order]
    shingles = [shingle_set(t.encode(), shingle_k) for t in texts]
    oracle = {
        (i, j)
        for i in range(len(texts))
        for j in range(i + 1, len(texts))
        if jaccard(shingles[i], shingles[j]) >= threshold
    }
    return texts, oracle


class CanaryProber:
    """Continuous quality prober over a live resolution path.

    ``resolve(texts) → int reps[N]`` — the live engine's certified
    one-shot (same-rep docs are predicted dup pairs).  ``index_run``
    (optional) pushes the corpus through a ``canary:``-space index /
    fleet client (``texts → attr``), proving the wire+index plane live;
    ``wipe()`` (optional, paired) expires those postings after scoring —
    :meth:`run_round` always calls it, success or not.
    """

    def __init__(
        self,
        resolve,
        *,
        index_run=None,
        wipe=None,
        registry=None,
        seed: int = 0,
        families: int = 6,
        members: int = 4,
        distractors: int = 8,
        shingle_k: int = 8,
        threshold: float = 0.7,
    ):
        from advanced_scrapper_tpu.obs import telemetry

        self._resolve = resolve
        self._index_run = index_run
        self._wipe = wipe
        self._reg = registry or telemetry.REGISTRY
        self.seed = int(seed)
        self.families = int(families)
        self.members = int(members)
        self.distractors = int(distractors)
        self.shingle_k = int(shingle_k)
        self.threshold = float(threshold)
        self.rounds = 0
        self.last_sli: dict = {}
        self._lock = threading.Lock()

    # -- metric handles (generation-checked: a registry reset in tests
    # must not strand increments on stale objects) ------------------------

    def _metrics(self):
        reg = self._reg
        return {
            "recall": reg.gauge(
                "astpu_canary_recall",
                "last canary round's pair recall vs the measured oracle "
                "(ground-truth synthetic families; always-on quality SLI)",
                always=True,
            ),
            "precision": reg.gauge(
                "astpu_canary_precision",
                "last canary round's pair precision vs the measured oracle",
                always=True,
            ),
            "latency": reg.histogram(
                "astpu_canary_latency_seconds",
                "end-to-end canary round latency (generate → resolve → "
                "score → expire)",
                always=True,
            ),
            "rounds": reg.counter(
                "astpu_canary_rounds_total",
                "canary probe rounds completed",
                always=True,
            ),
            "wiped": reg.counter(
                "astpu_canary_postings_wiped_total",
                "canary-space postings expired after probe rounds (the "
                "no-pollution proof-of-work)",
                always=True,
            ),
        }

    def run_round(self, round_id: int | None = None) -> dict:
        """One probe round; returns (and exports) the SLI dict:
        ``{round, recall, precision, latency_seconds, oracle_pairs,
        predicted_pairs, caught_pairs, index_dups, wiped}``."""
        with self._lock:
            rid = self.rounds if round_id is None else int(round_id)
            m = self._metrics()
            t0 = time.perf_counter()
            texts, oracle = make_canary_corpus(
                self.seed + rid,
                families=self.families,
                members=self.members,
                distractors=self.distractors,
                shingle_k=self.shingle_k,
                threshold=self.threshold,
            )
            wiped = 0
            try:
                reps = np.asarray(self._resolve(texts))
                n = len(texts)
                pred = {
                    (i, j)
                    for i in range(n)
                    for j in range(i + 1, n)
                    if reps[i] == reps[j]
                }
                index_dups = -1
                if self._index_run is not None:
                    attr = np.asarray(self._index_run(texts))
                    index_dups = int((attr >= 0).sum())
            finally:
                # expiry is unconditional: a raised round must not leave
                # synthetic postings behind
                if self._wipe is not None:
                    try:
                        wiped = int(self._wipe())
                    except Exception:
                        wiped = -1
            caught = len(pred & oracle)
            recall = caught / len(oracle) if oracle else 1.0
            precision = caught / len(pred) if pred else 1.0
            latency = time.perf_counter() - t0
            self.rounds = rid + 1
            sli = {
                "round": rid,
                "recall": recall,
                "precision": precision,
                "latency_seconds": latency,
                "oracle_pairs": len(oracle),
                "predicted_pairs": len(pred),
                "caught_pairs": caught,
                "index_dups": index_dups,
                "wiped": wiped,
            }
            self.last_sli = sli
            m["recall"].set(recall)
            m["precision"].set(precision)
            m["latency"].observe(latency)
            m["rounds"].inc()
            if wiped > 0:
                m["wiped"].inc(wiped)
            return sli

    def objectives(
        self,
        *,
        recall_min: float = 0.9,
        precision_min: float = 0.9,
        budget: float = 0.05,
    ) -> list:
        """Declared quality objectives for the PR 11 SLO engine:
        ``gauge_min`` over the canary SLIs (violated while the live
        gauge sits under the floor; burn rates over the engine's
        fast/slow windows)."""
        from advanced_scrapper_tpu.obs.slo import SloObjective

        return [
            SloObjective(
                name="canary_recall",
                kind="gauge_min",
                metric="astpu_canary_recall",
                threshold=float(recall_min),
                budget=budget,
            ),
            SloObjective(
                name="canary_precision",
                kind="gauge_min",
                metric="astpu_canary_precision",
                threshold=float(precision_min),
                budget=budget,
            ),
        ]
