"""Declarative SLO engine: objectives as data, evaluated over metrics.

The telemetry plane answers "what is the p99"; this module answers "is the
p99 *acceptable*, and how fast are we burning the error budget".
Objectives are declared as plain dicts (JSON-able — a config file, a bench
table, a crashsweep battery) and evaluated over *flat Prometheus samples*
— the one representation shared by a live process registry
(``telemetry.Registry.prometheus_text`` → ``collector.parse_prometheus_text``)
and the fleet collector's merged view — so the SAME objective definition
gates a single process, a bench run, and a 2×N fleet.

Objective kinds:

- ``p99_latency_max`` — p99 of a histogram ≤ ``threshold`` seconds,
  computed over the *window delta* of the cumulative buckets between
  evaluations (a cumulative histogram never forgets; an SLO must — a
  violated-then-recovered latency regression has to read as recovered);
- ``rate_min`` — a counter's per-second rate ≥ ``threshold`` (throughput
  floors per regime);
- ``rate_max`` — a counter's per-second rate ≤ ``threshold`` (event
  ceilings: ``threshold: 0`` on ``astpu_jit_compiles_total`` is the
  recompile-storm alarm — any steady-state compile between evaluations
  violates, which is exactly what the sentinel exists to surface);
- ``ratio_max`` — delta(``metric``)/delta(``denominator``) ≤ ``threshold``
  (error-ratio budgets);
- ``gauge_min`` / ``gauge_max`` — an aggregated gauge vs a floor/ceiling
  (fleet health floors: ``shards_healthy`` ≥ N).

**Burn rate** follows the multi-window idiom: each objective keeps a
history of per-evaluation verdicts; ``burn = violating fraction of the
window / budget`` for a fast and a slow window, and the objective is
*alerting* only when BOTH exceed 1 — a blip trips the fast window but not
the slow one, a slow leak trips both.

Every evaluation exports ``astpu_slo_compliant`` / ``astpu_slo_value`` /
``astpu_slo_burn_rate{window=fast|slow}`` / ``astpu_slo_violations_total``
series (``objective=<name>`` labels) into a registry, and returns a
machine-readable verdict dict — what bench embeds in its result JSON and
the crashsweep battery asserts on.
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field

__all__ = [
    "SloObjective",
    "SloEngine",
    "load_objectives",
    "percentile_from_buckets",
]

KINDS = (
    "p99_latency_max", "rate_min", "rate_max", "ratio_max",
    "gauge_min", "gauge_max",
)


@dataclass
class SloObjective:
    """One objective, declared as data.

    ``labels`` is a subset match: a sample counts when every (k, v) here
    appears in its labels — so one objective can span every ``instance``
    of a fleet-merged series, or pin one shard with
    ``labels={"instance": "s0n0"}``.
    """

    name: str
    kind: str                  # one of KINDS
    metric: str                # base metric name (histograms: WITHOUT _bucket)
    threshold: float
    labels: dict = field(default_factory=dict)
    denominator: str | None = None   # ratio_max only: the total-series name
    agg: str = "sum"           # gauge aggregation across matches: sum|min|max
    budget: float = 0.05       # allowed violating fraction of a window
    fast_window: float = 30.0  # seconds
    slow_window: float = 300.0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"objective {self.name!r}: unknown kind {self.kind!r}")
        if self.kind == "ratio_max" and not self.denominator:
            raise ValueError(
                f"objective {self.name!r}: ratio_max needs a denominator"
            )
        if self.budget <= 0:
            raise ValueError(f"objective {self.name!r}: budget must be > 0")

    @classmethod
    def from_dict(cls, d: dict) -> "SloObjective":
        return cls(**{k: v for k, v in d.items()})

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "metric": self.metric,
            "threshold": self.threshold,
            "labels": dict(self.labels),
            "denominator": self.denominator,
            "agg": self.agg,
            "budget": self.budget,
            "fast_window": self.fast_window,
            "slow_window": self.slow_window,
        }


def load_objectives(data) -> list[SloObjective]:
    """A list of dicts (or ready objectives) → objectives; the declarative
    entry point bench/crashsweep/tools feed from JSON."""
    out = []
    for d in data:
        out.append(d if isinstance(d, SloObjective) else SloObjective.from_dict(d))
    names = [o.name for o in out]
    if len(names) != len(set(names)):
        raise ValueError(f"duplicate objective names in {names}")
    return out


def percentile_from_buckets(buckets: list[tuple[float, float]], q: float) -> float:
    """q-quantile from ``[(le_bound_seconds, count_in_bucket)]`` (NON-
    cumulative counts, sorted by bound; +Inf allowed as ``math.inf``);
    linear interpolation inside the containing bucket, 0.0 when empty."""
    total = sum(n for _b, n in buckets)
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0.0
    lo = 0.0
    for bound, n in buckets:
        if n > 0 and cum + n >= target:
            hi = bound if not math.isinf(bound) else lo * 2 or 1.0
            return lo + (hi - lo) * ((target - cum) / n)
        cum += n
        lo = bound if not math.isinf(bound) else lo
    return lo


def _matches(labels: dict, want: dict) -> bool:
    return all(labels.get(k) == str(v) or labels.get(k) == v for k, v in want.items())


class _ObjState:
    __slots__ = ("prev_counters", "prev_buckets", "history", "violations")

    def __init__(self):
        self.prev_counters: dict | None = None  # series key → value
        self.prev_buckets: dict | None = None   # le → cumulative count
        self.history: deque = deque()           # (ts, violated bool)
        self.violations = 0


class SloEngine:
    """Evaluate declared objectives over flat samples; export + verdict."""

    def __init__(self, objectives, *, registry=None, export: bool = True):
        """``registry``: where the ``astpu_slo_*`` series land (default:
        the process registry — always-on, like event counters: an engine
        only exists because an operator declared objectives).  ``export=
        False`` keeps the engine side-effect free (pure verdicts for
        tests and bench's embedded snapshot)."""
        from advanced_scrapper_tpu.obs import telemetry

        self.objectives = load_objectives(objectives)
        self._state = {o.name: _ObjState() for o in self.objectives}
        self._prev_ts: float | None = None
        self.last_verdict: dict | None = None
        self._export = export
        self._reg = registry or telemetry.REGISTRY
        self._m: dict[tuple, object] = {}
        if export:
            for o in self.objectives:
                self._m[("compliant", o.name)] = self._reg.gauge(
                    "astpu_slo_compliant",
                    "1 = objective met at last evaluation, 0 = violated, "
                    "-1 = no data (the selected series do not exist — a "
                    "typo'd metric must never read as green)",
                    always=True, objective=o.name,
                )
                self._m[("value", o.name)] = self._reg.gauge(
                    "astpu_slo_value",
                    "the measured value the objective compares",
                    always=True, objective=o.name,
                )
                self._m[("viol", o.name)] = self._reg.counter(
                    "astpu_slo_violations_total",
                    "evaluations that found the objective violated",
                    always=True, objective=o.name,
                )
                for w in ("fast", "slow"):
                    self._m[(f"burn_{w}", o.name)] = self._reg.gauge(
                        "astpu_slo_burn_rate",
                        "violating window fraction / error budget "
                        "(>1 in BOTH windows = alerting)",
                        always=True, objective=o.name, window=w,
                    )

    # -- sample sources ----------------------------------------------------

    @staticmethod
    def registry_samples(registry=None):
        """Flatten a live :class:`~.telemetry.Registry` into the SAME flat
        samples the collector serves — one code path for both sources."""
        from advanced_scrapper_tpu.obs import collector, telemetry

        reg = registry or telemetry.REGISTRY
        samples, _types, _ex = collector.parse_prometheus_text(
            reg.prometheus_text()
        )
        return samples

    # -- evaluation --------------------------------------------------------

    def _eval_p99(self, o: SloObjective, st: _ObjState, samples):
        # aggregate cumulative bucket counts per `le` across every
        # matching series (all instances of a fleet-merged histogram)
        cum: dict[float, float] = {}
        for name, labels, v in samples:
            if name != f"{o.metric}_bucket":
                continue
            le = labels.get("le")
            if le is None or not _matches(
                {k: v2 for k, v2 in labels.items() if k != "le"}, o.labels
            ):
                continue
            bound = math.inf if le == "+Inf" else float(le)
            cum[bound] = cum.get(bound, 0.0) + v
        if not cum:
            return None, None  # no data
        prev = st.prev_buckets or {}
        st.prev_buckets = dict(cum)
        bounds = sorted(cum)
        # window delta (cumulative-within-series AND cumulative-across-
        # bounds): de-cumulate across bounds first, then subtract the
        # previous window's de-cumulated counts
        def decum(c: dict) -> list[tuple[float, float]]:
            out, last = [], 0.0
            for b in sorted(c):
                out.append((b, max(0.0, c[b] - last)))
                last = c[b]
            return out

        cur_counts = dict(decum(cum))
        prev_counts = dict(decum(prev)) if prev else {}
        window = [
            (b, max(0.0, cur_counts.get(b, 0.0) - prev_counts.get(b, 0.0)))
            for b in bounds
        ]
        if sum(n for _b, n in window) <= 0:
            # nothing happened this window: an idle service is compliant,
            # not violating (and not "no data" — the series exists)
            return 0.0, False
        p99 = percentile_from_buckets(window, 0.99)
        return p99, p99 > o.threshold

    def _eval_counter_sum(self, o, samples, name):
        total = 0.0
        found = False
        for n, labels, v in samples:
            if n == name and _matches(labels, o.labels):
                total += v
                found = True
        return total if found else None

    def _eval_rate(self, o: SloObjective, st: _ObjState, samples, dt):
        cur = self._eval_counter_sum(o, samples, o.metric)
        if cur is None:
            return None, None
        prev = (st.prev_counters or {}).get("rate")
        st.prev_counters = {"rate": cur}
        if prev is None or dt is None or dt <= 0:
            return None, None  # first sight: no rate yet
        rate = max(0.0, cur - prev) / dt
        if o.kind == "rate_max":
            return rate, rate > o.threshold
        return rate, rate < o.threshold

    def _eval_ratio(self, o: SloObjective, st: _ObjState, samples):
        num = self._eval_counter_sum(o, samples, o.metric)
        den = self._eval_counter_sum(o, samples, o.denominator)
        if num is None and den is None:
            return None, None
        num = num or 0.0
        den = den or 0.0
        prev = st.prev_counters or {}
        st.prev_counters = {"num": num, "den": den}
        dnum = max(0.0, num - prev.get("num", 0.0)) if prev else num
        dden = max(0.0, den - prev.get("den", 0.0)) if prev else den
        ratio = (dnum / dden) if dden > 0 else (math.inf if dnum > 0 else 0.0)
        return ratio, ratio > o.threshold

    def _eval_gauge(self, o: SloObjective, samples):
        vals = [
            v
            for n, labels, v in samples
            if n == o.metric and _matches(labels, o.labels)
        ]
        if not vals:
            return None, None
        agg = {"sum": sum, "min": min, "max": max}[o.agg](vals)
        if o.kind == "gauge_min":
            return agg, agg < o.threshold
        return agg, agg > o.threshold

    def evaluate(self, samples=None, *, now: float | None = None) -> dict:
        """One evaluation round → the machine-readable verdict.

        ``samples``: flat ``[(name, labels, value)]`` (a collector's
        :meth:`~.collector.FleetCollector.merged_samples` first element,
        or :meth:`registry_samples`); default = the process registry.
        """
        if samples is None:
            samples = self.registry_samples()
        now = time.time() if now is None else now
        dt = (now - self._prev_ts) if self._prev_ts is not None else None
        self._prev_ts = now
        objectives = []
        all_ok = True
        alerting = []
        for o in self.objectives:
            st = self._state[o.name]
            if o.kind == "p99_latency_max":
                value, violated = self._eval_p99(o, st, samples)
            elif o.kind in ("rate_min", "rate_max"):
                value, violated = self._eval_rate(o, st, samples, dt)
            elif o.kind == "ratio_max":
                value, violated = self._eval_ratio(o, st, samples)
            else:
                value, violated = self._eval_gauge(o, samples)
            if violated is not None:
                st.history.append((now, bool(violated)))
                if violated:
                    st.violations += 1
                    if self._export:
                        self._m[("viol", o.name)].inc()
            horizon = now - max(o.fast_window, o.slow_window)
            while st.history and st.history[0][0] < horizon:
                st.history.popleft()

            def frac(window: float) -> float:
                cut = now - window
                pts = [v for ts, v in st.history if ts >= cut]
                return (sum(pts) / len(pts)) if pts else 0.0

            burn_fast = frac(o.fast_window) / o.budget
            burn_slow = frac(o.slow_window) / o.budget
            ok = (violated is False) if violated is not None else None
            if violated:
                all_ok = False
            is_alerting = burn_fast > 1.0 and burn_slow > 1.0
            if is_alerting:
                alerting.append(o.name)
            if self._export:
                self._m[("compliant", o.name)].set(
                    -1.0 if violated is None else (0.0 if violated else 1.0)
                )
                if value is not None and not math.isinf(value):
                    self._m[("value", o.name)].set(float(value))
                self._m[("burn_fast", o.name)].set(burn_fast)
                self._m[("burn_slow", o.name)].set(burn_slow)
            objectives.append(
                {
                    "name": o.name,
                    "kind": o.kind,
                    "metric": o.metric,
                    "threshold": o.threshold,
                    "value": (
                        None if value is None
                        else (float(value) if not math.isinf(value) else "inf")
                    ),
                    "ok": ok,
                    "violations": st.violations,
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "alerting": is_alerting,
                }
            )
        self.last_verdict = {
            "ts": now,
            "ok": all_ok,
            "alerting": alerting,
            "objectives": objectives,
        }
        return self.last_verdict
