"""Fleet-wide metrics aggregation: one scrape plane over every process.

PR 3's telemetry made each process observable; PRs 5-10 made the system a
multi-process fleet (RPC index shards, lease servers, scraper workers,
bench children) whose ``/metrics`` endpoints were islands.  This module is
the pull-based collector that merges them:

- **discovery**: endpoints are added explicitly (``add_endpoint``), parsed
  from a comma/semicolon list of urls, or discovered from an *obs dir* —
  every :class:`~.telemetry.StatusServer` under ``ASTPU_OBS_DIR`` drops a
  ``<name>.endpoint`` file after its listen succeeds, so the collector
  never races an ephemeral bind and never needs a port registry;
- **scrape + merge**: each endpoint's ``GET /metrics`` (Prometheus text)
  is pulled concurrently under a per-endpoint timeout and re-served from
  ONE merged view with an ``instance=<name>`` label on every series, so
  two shards exporting the same series name can never collide;
- **staleness, not blocking**: a dead endpoint (mid-failover, SIGKILLed)
  costs one timeout in the background scrape loop — serving always reads
  the cached last-known samples, flagged by ``astpu_collector_endpoint_up
  {instance}`` and ``astpu_collector_scrape_age_seconds{instance}``, so a
  scrape during failover returns partial results with a staleness marker
  instead of hanging the dashboard;
- **crash-sidecar harvesting**: flight-recorder JSONL dumps
  (``obs/trace.py``) written by dying processes are pulled centrally from
  a sidecar directory; the harvest names the dead shard (the
  ``shard.serve`` event every :class:`~..index.remote.IndexShardServer`
  records at start) so a chaos kill is attributable from the collector's
  ``/status`` alone.

The merged view is itself served on ``GET /metrics`` + ``/status``
(:meth:`FleetCollector.serve`), which is also what the SLO engine
(``obs/slo.py``) and ``obs_top --fleet`` evaluate/render.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
import urllib.request

__all__ = [
    "FleetCollector",
    "parse_prometheus_text",
    "parse_endpoint_list",
]

#: one parsed series sample: (name, labels, value)
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_EXEMPLAR_RE = re.compile(
    r"^# exemplar (?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r'\s+trace="(?P<trace>[^"]*)" value=(?P<value>[^\s]+) ts=(?P<ts>[^\s]+)'
)


def _escape_label(v) -> str:
    """Inverse of :func:`_parse_labels`' unescaping — label values round-
    trip through the collector unchanged (quotes/backslashes included)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _parse_labels(raw: str | None) -> dict:
    if not raw:
        return {}
    return {
        k: v.replace('\\"', '"').replace("\\\\", "\\")
        for k, v in _LABEL_RE.findall(raw)
    }


def parse_prometheus_text(text: str):
    """Parse Prometheus exposition text → ``(samples, types, exemplars)``.

    ``samples`` is ``[(name, labels, value)]`` (histogram ``_bucket`` /
    ``_sum`` / ``_count`` series appear as plain samples — exactly the
    shape the merge re-serves); ``types`` maps base metric name → kind
    from ``# TYPE`` lines; ``exemplars`` is the slow-call exemplar
    comment lines (``obs/telemetry.py``) as dicts.  Unparseable lines are
    skipped, never raised — a half-written or foreign exporter must not
    poison the whole merge."""
    samples: list[tuple[str, dict, float]] = []
    types: dict[str, str] = {}
    exemplars: list[dict] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            if line.startswith("# TYPE "):
                parts = line.split()
                if len(parts) >= 4:
                    types[parts[2]] = parts[3]
            else:
                m = _EXEMPLAR_RE.match(line)
                if m:
                    try:
                        exemplars.append(
                            {
                                "name": m.group("name"),
                                "labels": _parse_labels(m.group("labels")),
                                "trace": m.group("trace"),
                                "value": float(m.group("value")),
                                "ts": float(m.group("ts")),
                            }
                        )
                    except ValueError:
                        pass
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        try:
            v = float(m.group("value"))
        except ValueError:
            continue
        samples.append((m.group("name"), _parse_labels(m.group("labels")), v))
    return samples, types, exemplars


def parse_endpoint_list(spec: str) -> list[tuple[str, str]]:
    """``name=url,name=url`` (or bare urls, named by host:port) → pairs."""
    out = []
    for part in re.split(r"[,;]", spec):
        part = part.strip()
        if not part:
            continue
        if "=" in part and not part.startswith("http"):
            name, _, url = part.partition("=")
        else:
            name, url = "", part
        if not url.startswith("http"):
            url = f"http://{url}"
        if not name:
            name = url.split("://", 1)[-1].rstrip("/")
        out.append((name, url))
    return out


class _Endpoint:
    """Per-endpoint scrape state; mutated only by the scrape path, read
    (under the collector lock) by the serve path."""

    __slots__ = (
        "name", "url", "samples", "types", "exemplars", "ok", "error",
        "last_ok", "last_attempt", "scrapes", "failures",
        "profile", "profile_ok",
    )

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url.rstrip("/")
        self.samples: list = []
        self.types: dict = {}
        self.exemplars: list = []
        self.ok = False
        self.error = ""
        self.last_ok = 0.0       # monotonic stamp of the last good scrape
        self.last_attempt = 0.0
        self.scrapes = 0
        self.failures = 0
        self.profile = ""        # last-known /profile folded text
        self.profile_ok = False


class FleetCollector:
    """Scrape N ``/metrics`` endpoints, merge them under ``instance``
    labels, harvest crash sidecars, serve the fleet-wide view."""

    def __init__(
        self,
        endpoints=(),
        *,
        timeout: float = 2.0,
        obs_dir: str | None = None,
        sidecar_dir: str | None = None,
        stale_after: float = 15.0,
        profiles: bool = False,
    ):
        """``endpoints``: iterable of ``(name, url)`` pairs or bare urls.
        ``obs_dir``: directory of ``*.endpoint`` announcement files,
        re-scanned on every scrape round (new processes join the merge
        without a restart).  ``sidecar_dir``: where dying processes'
        flight-recorder JSONL dumps land (``ASTPU_FLIGHT_RECORDER``);
        scanned by :meth:`harvest_sidecars`.  ``stale_after``: seconds
        without a good scrape before an endpoint's cached samples are
        flagged stale in ``/status``.  ``profiles``: also pull each
        endpoint's ``GET /profile`` (the continuous host profiler,
        ``obs/profiler.py``) every scrape round and serve the merged
        per-instance folded stacks on the collector's own ``/profile``
        (off by default — profile bodies are bigger than metrics and only
        exist under ``ASTPU_PROFILE``; :meth:`harvest_profiles` is always
        callable on demand)."""
        self.timeout = timeout
        self.obs_dir = obs_dir
        self.sidecar_dir = sidecar_dir
        self.stale_after = stale_after
        self.profiles = profiles
        self._lock = threading.Lock()
        self._endpoints: dict[str, _Endpoint] = {}
        self._sidecars: dict[str, dict] = {}  # path → harvested summary
        self._rounds = 0
        self._stop = threading.Event()
        self._loop_thread: threading.Thread | None = None
        self._server = None
        for ep in endpoints:
            if isinstance(ep, str):
                for name, url in parse_endpoint_list(ep):
                    self.add_endpoint(name, url)
            else:
                self.add_endpoint(*ep)

    # -- topology ----------------------------------------------------------

    def add_endpoint(self, name: str, url: str) -> None:
        with self._lock:
            if name not in self._endpoints:
                self._endpoints[name] = _Endpoint(name, url)

    def discover(self) -> int:
        """Scan the obs dir for ``*.endpoint`` files; returns how many NEW
        endpoints joined.  A vanished file does not remove the endpoint —
        its staleness marker is the honest signal (the process may be
        mid-crash with its dump still worth harvesting)."""
        if not self.obs_dir or not os.path.isdir(self.obs_dir):
            return 0
        added = 0
        for fn in sorted(os.listdir(self.obs_dir)):
            if not fn.endswith(".endpoint"):
                continue
            name = fn[: -len(".endpoint")]
            with self._lock:
                known = name in self._endpoints
            if known:
                continue
            try:
                with open(os.path.join(self.obs_dir, fn), encoding="utf-8") as fh:
                    url = fh.readline().strip()
            except OSError:
                continue
            if url.startswith("http"):
                self.add_endpoint(name, url)
                added += 1
        return added

    # -- scraping ----------------------------------------------------------

    def _scrape_endpoint(self, ep: _Endpoint) -> None:
        ep.last_attempt = time.monotonic()
        ep.scrapes += 1
        try:
            with urllib.request.urlopen(
                ep.url + "/metrics", timeout=self.timeout
            ) as r:
                text = r.read().decode("utf-8", errors="replace")
            samples, types, exemplars = parse_prometheus_text(text)
        except Exception as e:  # noqa: BLE001 — any fetch fault = endpoint down
            with self._lock:
                ep.ok = False
                ep.error = f"{type(e).__name__}: {e}"
                ep.failures += 1
            return
        with self._lock:
            ep.samples = samples
            ep.types = types
            ep.exemplars = exemplars
            ep.ok = True
            ep.error = ""
            ep.last_ok = time.monotonic()

    def scrape_once(self) -> dict:
        """One concurrent scrape round over every known endpoint (after a
        discovery pass); returns ``{endpoint: ok}``.  Bounded by the
        per-endpoint timeout — one dark shard costs one timeout, in
        parallel with the live scrapes, never a serial stall."""
        self.discover()
        with self._lock:
            eps = list(self._endpoints.values())
        threads = [
            threading.Thread(target=self._scrape_endpoint, args=(ep,), daemon=True)
            for ep in eps
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout + 1.0)
        if self.sidecar_dir:
            self.harvest_sidecars()
        if self.profiles:
            self.harvest_profiles()
        with self._lock:
            self._rounds += 1
            return {ep.name: ep.ok for ep in eps}

    # -- profile harvest ---------------------------------------------------

    def _fetch_profile(self, ep: _Endpoint) -> None:
        try:
            with urllib.request.urlopen(
                ep.url + "/profile", timeout=self.timeout
            ) as r:
                text = r.read().decode("utf-8", errors="replace")
        except Exception:
            with self._lock:
                ep.profile_ok = False
            return
        with self._lock:
            ep.profile = text
            ep.profile_ok = True

    def harvest_profiles(self) -> dict:
        """Pull every endpoint's ``GET /profile`` (concurrently, same
        per-endpoint timeout discipline as the metrics scrape); returns
        ``{endpoint: ok}``.  A dead or profile-less endpoint keeps its
        last-known folded stacks — the merged view is a fleet snapshot,
        staleness travels with the metrics-side markers."""
        with self._lock:
            eps = list(self._endpoints.values())
        threads = [
            threading.Thread(target=self._fetch_profile, args=(ep,), daemon=True)
            for ep in eps
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout + 1.0)
        with self._lock:
            return {ep.name: ep.profile_ok for ep in eps}

    def merged_profile(self) -> str:
        """The fleet-wide folded-stack view: every endpoint's last-known
        ``/profile`` body with the instance name prefixed onto each stack
        (``instance;root;...;leaf count``) — one text a flamegraph tool
        renders with per-process towers side by side.  Endpoint header
        comments are kept, re-tagged per instance."""
        lines: list[str] = []
        with self._lock:
            eps = list(self._endpoints.values())
        for ep in eps:
            if not ep.profile:
                continue
            for line in ep.profile.splitlines():
                line = line.strip()
                if not line:
                    continue
                if line.startswith("#"):
                    lines.append(f"# instance={ep.name} {line.lstrip('# ')}")
                else:
                    lines.append(f"{ep.name};{line}")
        return "\n".join(lines) + "\n"

    # -- sidecar harvest ---------------------------------------------------

    def harvest_sidecars(self) -> list[dict]:
        """Pull flight-recorder JSONL dumps from the sidecar dir into the
        collector's state: each dump is summarized (pid, reason, event
        count, every ``shard``/``graph`` name seen in its events) so the
        fleet view NAMES what died.  Cached by (size, mtime); a dump is
        re-read only when it grew (a process can dump once per death, but
        several processes may share a file via append)."""
        if not self.sidecar_dir or not os.path.isdir(self.sidecar_dir):
            return []
        for root, _dirs, files in os.walk(self.sidecar_dir):
            for fn in sorted(files):
                if not fn.endswith(".jsonl"):
                    continue
                path = os.path.join(root, fn)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                key = (st.st_size, int(st.st_mtime))
                with self._lock:
                    prev = self._sidecars.get(path)
                if prev is not None and prev.get("_stat") == list(key):
                    continue
                summary = self._read_sidecar(path)
                if summary is None:
                    continue
                summary["_stat"] = list(key)
                with self._lock:
                    self._sidecars[path] = summary
        with self._lock:
            return [
                {k: v for k, v in s.items() if k != "_stat"}
                for _p, s in sorted(self._sidecars.items())
            ]

    @staticmethod
    def _read_sidecar(path: str) -> dict | None:
        dumps = 0
        pid = None
        reasons: list[str] = []
        shards: set[str] = set()
        events = 0
        faults: list[str] = []
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                for line in fh:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue  # an OS-cut tail line stays tolerable
                    if not isinstance(ev, dict):
                        continue
                    events += 1
                    if ev.get("kind") == "dump":
                        dumps += 1
                        pid = ev.get("pid", pid)
                        if ev.get("reason"):
                            reasons.append(str(ev["reason"]))
                    elif ev.get("kind") == "fault":
                        faults.append(str(ev.get("reason", ev.get("name"))))
                    # DEATH attribution only — never routine traffic: a
                    # shard names ITSELF via its shard.serve event (its
                    # dump exists because it died), and a surviving
                    # client names dead PEERS via failover events.  A
                    # client's fleet.probe/insert spans name every shard
                    # it ever touched and must not count.
                    if "shard" in ev and ev.get("name") in (
                        "shard.serve", "fleet.failover"
                    ):
                        shards.add(str(ev["shard"]))
        except OSError:
            return None
        if events == 0:
            return None
        return {
            "path": path,
            "name": os.path.basename(path),
            "pid": pid,
            "dumps": dumps,
            "reasons": reasons[-3:],
            "faults": faults[-3:],
            "shards": sorted(shards),
            "events": events,
        }

    def dead_shards(self) -> list[str]:
        """Every shard name appearing in a harvested crash dump — the
        "which shard died" answer the chaos battery asserts on."""
        with self._lock:
            out: set[str] = set()
            for s in self._sidecars.values():
                out.update(s.get("shards", ()))
            return sorted(out)

    # -- merged views ------------------------------------------------------

    def _self_samples(self):
        """The collector's own always-on series (computed, not stored: the
        collector aggregates OTHER registries and must not also race the
        process-local one)."""
        now = time.monotonic()
        samples: list[tuple[str, dict, float]] = []
        types = {
            "astpu_collector_endpoint_up": "gauge",
            "astpu_collector_scrape_age_seconds": "gauge",
            "astpu_collector_scrape_failures_total": "counter",
            "astpu_collector_endpoints": "gauge",
            "astpu_collector_rounds_total": "counter",
            "astpu_collector_sidecar_dumps": "gauge",
            "astpu_collector_series": "gauge",
        }
        with self._lock:
            eps = list(self._endpoints.values())
            n_series = sum(len(ep.samples) for ep in eps)
            for ep in eps:
                lab = {"instance": ep.name}
                samples.append(
                    ("astpu_collector_endpoint_up", lab, 1.0 if ep.ok else 0.0)
                )
                age = (now - ep.last_ok) if ep.last_ok else float("inf")
                samples.append(
                    (
                        "astpu_collector_scrape_age_seconds",
                        lab,
                        age if age != float("inf") else -1.0,
                    )
                )
                samples.append(
                    ("astpu_collector_scrape_failures_total", lab, float(ep.failures))
                )
            samples.append(("astpu_collector_endpoints", {}, float(len(eps))))
            samples.append(("astpu_collector_rounds_total", {}, float(self._rounds)))
            samples.append(
                ("astpu_collector_sidecar_dumps", {}, float(len(self._sidecars)))
            )
            samples.append(("astpu_collector_series", {}, float(n_series)))
        return samples, types

    def merged_samples(self):
        """Every endpoint's last-known samples with ``instance=<name>``
        stamped on, plus the collector's own series.  Dead endpoints keep
        serving their cache (partial results beat a blocking scrape); the
        ``astpu_collector_*`` series carry the staleness truth."""
        out, types = self._self_samples()
        with self._lock:
            for ep in self._endpoints.values():
                for name, labels, v in ep.samples:
                    out.append((name, {**labels, "instance": ep.name}, v))
                for n, k in ep.types.items():
                    types.setdefault(n, k)
        return out, types

    def prometheus_text(self) -> str:
        """The merged fleet registry in Prometheus text format (what the
        collector's own ``/metrics`` serves)."""
        samples, types = self.merged_samples()
        lines: list[str] = []
        typed: set[str] = set()
        for name, labels, v in samples:
            base = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and name[: -len(suffix)] in types:
                    base = name[: -len(suffix)]
                    break
            if base not in typed and base in types:
                typed.add(base)
                lines.append(f"# TYPE {base} {types[base]}")
            sv = (
                str(int(v))
                if math.isfinite(v) and v == int(v) and abs(v) < 1e15
                else repr(v)
            )
            if labels:
                inner = ",".join(
                    f'{k}="{_escape_label(v2)}"'
                    for k, v2 in sorted(labels.items())
                )
                lines.append(f"{name}{{{inner}}} {sv}")
            else:
                lines.append(f"{name} {sv}")
        with self._lock:
            for ep in self._endpoints.values():
                for ex in ep.exemplars:
                    inner = ",".join(
                        f'{k}="{_escape_label(v2)}"'
                        for k, v2 in sorted(
                            {**ex["labels"], "instance": ep.name}.items()
                        )
                    )
                    lines.append(
                        f"# exemplar {ex['name']}{{{inner}}} "
                        f'trace="{ex["trace"]}" value={ex["value"]!r} '
                        f"ts={ex['ts']!r}"
                    )
        return "\n".join(lines) + "\n"

    def status(self) -> dict:
        """JSON fleet view for ``/status``: per-endpoint health +
        staleness, merged series (flat), harvested sidecars."""
        now = time.monotonic()
        with self._lock:
            endpoints = []
            for ep in self._endpoints.values():
                age = (now - ep.last_ok) if ep.last_ok else None
                endpoints.append(
                    {
                        "name": ep.name,
                        "url": ep.url,
                        "ok": ep.ok,
                        "stale": (age is None) or (age > self.stale_after),
                        "age_s": round(age, 3) if age is not None else None,
                        "series": len(ep.samples),
                        "scrapes": ep.scrapes,
                        "failures": ep.failures,
                        "error": ep.error,
                    }
                )
            sidecars = [
                {k: v for k, v in s.items() if k != "_stat"}
                for _p, s in sorted(self._sidecars.items())
            ]
        samples, _types = self.merged_samples()
        return {
            "ts": time.time(),
            "pid": os.getpid(),
            "collector": True,
            "endpoints": endpoints,
            "dead_shards": self.dead_shards(),
            "sidecars": sidecars,
            "metrics": [
                {"name": n, "labels": l, "value": v} for n, l, v in samples
            ],
        }

    # -- serving -----------------------------------------------------------

    def serve(
        self, *, host: str = "127.0.0.1", port: int = 0, interval: float = 1.0
    ):
        """Start the background scrape loop + an HTTP exporter serving the
        MERGED ``/metrics`` and ``/status``; returns self (``.host`` /
        ``.port`` carry the bound address)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from advanced_scrapper_tpu.obs import telemetry

        collector = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    telemetry.send_http_payload(
                        self, 200,
                        collector.prometheus_text().encode("utf-8"),
                        telemetry.PROMETHEUS_CONTENT_TYPE,
                    )
                elif self.path == "/status":
                    telemetry.send_http_payload(
                        self, 200,
                        json.dumps(collector.status()).encode("utf-8"),
                        "application/json",
                    )
                elif self.path == "/profile":
                    telemetry.send_http_payload(
                        self, 200,
                        collector.merged_profile().encode("utf-8"),
                        "text/plain; charset=utf-8",
                    )
                else:
                    telemetry.send_http_payload(
                        self, 404,
                        json.dumps(
                            {"error": f"no such endpoint {self.path}"}
                        ).encode("utf-8"),
                        "application/json",
                    )

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="astpu-collector-http",
        ).start()
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                self.scrape_once()

        self.scrape_once()  # the first round is synchronous: serve real data
        self._loop_thread = threading.Thread(
            target=loop, daemon=True, name="astpu-collector-scrape"
        )
        self._loop_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
            self._loop_thread = None
