"""Per-stage wall-clock counters for the host→device path.

The bench JSON's ``stage_ms`` breakdown (encode / h2d / kernel / resolve /
matcher_build) comes from here: hot paths wrap their stage work in
:func:`timed` (or call :func:`add` directly), the bench resets before a
regime and snapshots after.  Attribution is **by call site**, not by a
global timeline: the pipelines overlap stages on purpose (that is the whole
point of the async design), so the per-stage sums can legitimately exceed
the end-to-end wall clock, and device "kernel" time is the time the host
spent *waiting* on device results (dispatch is async; a fully-hidden kernel
contributes ~0).  The numbers answer "where would another millisecond of
host work hurt", which is what the next PR needs — not a scheduler trace.

Since the telemetry plane landed this module is a thin VIEW over the
process registry: every :func:`add` lands in the always-on
``astpu_stage_seconds`` histogram (``obs/telemetry.py``), so the bench's
``stage_ms`` and the live ``/metrics`` stage series are the same numbers
by construction.  The histograms are cumulative (Prometheus-style);
:func:`reset` snapshots per-stage baselines and :func:`snapshot_ms`
reports the delta since — a windowed read over shared state, so two
concurrent windowed readers would see each other's time (the bench runs
its regimes serially; live scrapes read the cumulative series instead).

Thread-safe (the H2D put pool and DeviceFeed workers time from their own
threads); overhead is one ``perf_counter`` pair and a histogram update per
*batch*, noise against millisecond-scale stages.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from advanced_scrapper_tpu.obs import telemetry

_lock = threading.Lock()
_hists: dict[str, telemetry.Histogram] = {}
_baseline: dict[str, float] = {}  # per-stage cumulative sum at last reset()

#: canonical stage names (call sites may add others; these are the bench's)
STAGES = (
    "encode",
    "h2d",
    "kernel",
    "resolve",
    "matcher_build",
    "matcher_screen",
    "matcher_verify",
)


def _hist(stage: str) -> telemetry.Histogram:
    # local cache so the per-batch path skips the registry lock/lookup
    h = _hists.get(stage)
    if h is None:
        h = telemetry.stage_histogram(stage)
        with _lock:
            _hists[stage] = h
    return h


def add(stage: str, seconds: float) -> None:
    _hist(stage).observe(seconds)


@contextmanager
def timed(stage: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        add(stage, time.perf_counter() - t0)


def reset() -> None:
    """Start a measurement window: nothing is cleared (the live series
    stays cumulative); per-stage baselines are snapshotted instead."""
    with _lock:
        _baseline.clear()
        for h in telemetry.stage_histograms():
            _baseline[h.labels["stage"]] = h.sum


def snapshot_ms() -> dict[str, float]:
    """Cumulative per-stage milliseconds since the last :func:`reset`."""
    out: dict[str, float] = {}
    with _lock:
        for h in telemetry.stage_histograms():
            stage = h.labels["stage"]
            out[stage] = round((h.sum - _baseline.get(stage, 0.0)) * 1e3, 1)
    return dict(sorted(out.items()))


# -- device-traffic counters ---------------------------------------------
#
# Always-on (like the stage histograms): dispatch-count wins are gated
# NUMERICALLY — tier-1 tests assert the packed dedup AND matcher paths'
# per-tile traffic is 1 put + 1 dispatch, and the bench emits per-regime
# deltas — so the counters must exist whether or not telemetry is
# enabled.  The ``regime`` label names the instrumented call-site plane
# ("dedup" = the NearDupEngine hot path, "feed" = DeviceFeed staging,
# "matcher" = the entity-screen tile plane); bench maps the cumulative
# deltas onto its own regime keys.  Only EXPLICIT device traffic is
# counted: ``jax.device_put`` calls and jitted-step dispatches in the
# instrumented pipelines — implicit transfers (numpy passed straight to
# a jit) are exactly the shape the packed paths exist to avoid, and
# counting them would hide that.  (One scoped exception: the LEGACY
# matcher refine slices count their jit-arg transfers explicitly in
# ``pipeline.matcher._refine_batch`` so the packed-vs-legacy matcher
# ledger compares like for like.)

_DEV_NAMES = (
    "astpu_device_puts_total",
    "astpu_device_dispatches_total",
    "astpu_h2d_bytes_total",
)
_dev_counters: dict[tuple[str, str, str | None], telemetry.Counter] = {}


def _dev(name: str, regime: str, shard: str | None = None) -> telemetry.Counter:
    c = _dev_counters.get((name, regime, shard))
    if c is None:
        labels = {"regime": regime}
        if shard is not None:
            # the mesh-sharded planes label traffic per device shard, so
            # the per-shard 1-put/1-dispatch contract is a ledger fact
            labels["shard"] = shard
        c = telemetry.event_counter(
            name,
            {
                "astpu_device_puts_total": "explicit jax.device_put calls",
                "astpu_device_dispatches_total": "jitted device dispatches",
                "astpu_h2d_bytes_total": "host→device bytes shipped by puts",
            }[name],
            **labels,
        )
        with _lock:
            _dev_counters[(name, regime, shard)] = c
    return c


def count_device_put(
    nbytes: int, regime: str = "dedup", *, shard: int | str | None = None
) -> None:
    """Record one explicit ``jax.device_put`` of ``nbytes`` (``shard``:
    the mesh row-shard the buffer landed on, for the sharded planes)."""
    shard = None if shard is None else str(shard)
    _dev("astpu_device_puts_total", regime, shard).inc()
    _dev("astpu_h2d_bytes_total", regime, shard).inc(nbytes)


def count_dispatch(
    regime: str = "dedup", n: int = 1, *, shard: int | str | None = None
) -> None:
    """Record ``n`` jitted device dispatches (``shard``: the mesh
    row-shard that executed them — one partitioned launch executes once
    per device, so the sharded planes count it once per shard)."""
    _dev(
        "astpu_device_dispatches_total", regime,
        None if shard is None else str(shard),
    ).inc(n)


def device_counters() -> dict[str, float]:
    """Cumulative device-traffic totals, summed across ``regime`` labels:
    ``{"device_puts", "device_dispatches", "h2d_bytes"}``.  Subtract two
    snapshots to window a regime (the bench does)."""
    out = {"device_puts": 0.0, "device_dispatches": 0.0, "h2d_bytes": 0.0}
    short = {
        "astpu_device_puts_total": "device_puts",
        "astpu_device_dispatches_total": "device_dispatches",
        "astpu_h2d_bytes_total": "h2d_bytes",
    }
    for name, key in short.items():
        for c in telemetry.REGISTRY.find(name):
            out[key] += c.value
    return out


def regime_device_counters(regime: str) -> dict[str, float]:
    """Cumulative device-traffic totals for ONE regime label:
    ``{"device_puts", "device_dispatches", "h2d_bytes"}`` — the
    per-regime twin of :func:`device_counters`.  The rerank launch-count
    gate windows this (subtract two snapshots) to assert a settled
    corpus cost exactly ``tiles + 1`` puts and ``tiles + 1`` dispatches
    on the ``"rerank"`` plane regardless of what the dedup plane did in
    between."""
    short = {
        "astpu_device_puts_total": "device_puts",
        "astpu_device_dispatches_total": "device_dispatches",
        "astpu_h2d_bytes_total": "h2d_bytes",
    }
    out = {"device_puts": 0.0, "device_dispatches": 0.0, "h2d_bytes": 0.0}
    for name, key in short.items():
        for c in telemetry.REGISTRY.find(name):
            if c.labels.get("regime") == regime:
                out[key] += c.value
    return out


def sharded_device_counters(regime: str = "sharded") -> dict[str, dict[str, float]]:
    """Per-shard cumulative device-traffic totals for one regime:
    ``{shard: {"device_puts", "device_dispatches", "h2d_bytes"}}`` —
    only shard-labelled series count (the single-device planes never
    carry the label).  Subtract two snapshots to window a corpus; the
    sharded launch-count gates (tier-1 and the MULTICHIP dryrun) assert
    every shard's delta is exactly tiles + 1 / tiles + 1."""
    short = {
        "astpu_device_puts_total": "device_puts",
        "astpu_device_dispatches_total": "device_dispatches",
        "astpu_h2d_bytes_total": "h2d_bytes",
    }
    out: dict[str, dict[str, float]] = {}
    for name, key in short.items():
        for c in telemetry.REGISTRY.find(name):
            shard = c.labels.get("shard")
            if shard is None or c.labels.get("regime") != regime:
                continue
            per = out.setdefault(
                shard,
                {"device_puts": 0.0, "device_dispatches": 0.0, "h2d_bytes": 0.0},
            )
            per[key] += c.value
    return out


def record_sharded_put_skew(
    baseline: dict | None = None, regime: str = "sharded"
) -> float:
    """Max−min per-shard put count across the shard-labelled ledger,
    recorded on the always-on ``astpu_sharded_put_skew`` gauge — the
    bench's SLO hook: a balanced sharded plane (every shard exactly
    tiles + 1 puts) reads 0, and the declared ``gauge_max`` objective
    turns any imbalance into a machine-readable verdict.

    ``baseline`` — a prior :func:`sharded_device_counters` snapshot —
    windows the computation to the work since that snapshot, and only
    shards ACTIVE in the window count: cumulative totals would read a
    permanent false skew in any process that ran corpora on meshes with
    different shard counts (an 8-shard corpus then a 4-shard one leaves
    shards 4-7 forever behind, with every corpus perfectly balanced)."""
    per = sharded_device_counters(regime)
    base = baseline or {}
    puts = [
        v["device_puts"] - base.get(s, {}).get("device_puts", 0.0)
        for s, v in per.items()
    ]
    puts = [p for p in puts if p > 0]  # shards active in the window
    skew = (max(puts) - min(puts)) if puts else 0.0
    telemetry.REGISTRY.gauge(
        "astpu_sharded_put_skew",
        "max-min per-shard device_put count (0 = balanced sharded ledger)",
        always=True,
    ).set(skew)
    return skew


def _clear_for_tests() -> None:
    """Drop the handle cache and baselines after a registry reset, or
    cached handles would keep feeding histograms (and device counters)
    the registry no longer exports.  Runs AUTOMATICALLY on every
    ``telemetry.REGISTRY.reset()`` via the reset hook below — the manual
    call-it-yourself contract was a real test-ordering trap (an early
    test's reset silently zeroed every later test's device-ledger
    deltas)."""
    with _lock:
        _hists.clear()
        _baseline.clear()
        _dev_counters.clear()


telemetry.REGISTRY.add_reset_hook(_clear_for_tests)
