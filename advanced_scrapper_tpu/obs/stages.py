"""Per-stage wall-clock counters for the host→device path.

The bench JSON's ``stage_ms`` breakdown (encode / h2d / kernel / resolve /
matcher_build) comes from here: hot paths wrap their stage work in
:func:`timed` (or call :func:`add` directly), the bench resets before a
regime and snapshots after.  Attribution is **by call site**, not by a
global timeline: the pipelines overlap stages on purpose (that is the whole
point of the async design), so the per-stage sums can legitimately exceed
the end-to-end wall clock, and device "kernel" time is the time the host
spent *waiting* on device results (dispatch is async; a fully-hidden kernel
contributes ~0).  The numbers answer "where would another millisecond of
host work hurt", which is what the next PR needs — not a scheduler trace.

Thread-safe (the H2D put pool and DeviceFeed workers time from their own
threads); overhead is one ``perf_counter`` pair and a dict update per
*batch*, noise against millisecond-scale stages.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

_lock = threading.Lock()
_acc: dict[str, float] = {}

#: canonical stage names (call sites may add others; these are the bench's)
STAGES = ("encode", "h2d", "kernel", "resolve", "matcher_build")


def add(stage: str, seconds: float) -> None:
    with _lock:
        _acc[stage] = _acc.get(stage, 0.0) + seconds


@contextmanager
def timed(stage: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        add(stage, time.perf_counter() - t0)


def reset() -> None:
    with _lock:
        _acc.clear()


def snapshot_ms() -> dict[str, float]:
    """Cumulative per-stage milliseconds since the last :func:`reset`."""
    with _lock:
        return {k: round(v * 1e3, 1) for k, v in sorted(_acc.items())}
