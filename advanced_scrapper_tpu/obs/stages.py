"""Per-stage wall-clock counters for the host→device path.

The bench JSON's ``stage_ms`` breakdown (encode / h2d / kernel / resolve /
matcher_build) comes from here: hot paths wrap their stage work in
:func:`timed` (or call :func:`add` directly), the bench resets before a
regime and snapshots after.  Attribution is **by call site**, not by a
global timeline: the pipelines overlap stages on purpose (that is the whole
point of the async design), so the per-stage sums can legitimately exceed
the end-to-end wall clock, and device "kernel" time is the time the host
spent *waiting* on device results (dispatch is async; a fully-hidden kernel
contributes ~0).  The numbers answer "where would another millisecond of
host work hurt", which is what the next PR needs — not a scheduler trace.

Since the telemetry plane landed this module is a thin VIEW over the
process registry: every :func:`add` lands in the always-on
``astpu_stage_seconds`` histogram (``obs/telemetry.py``), so the bench's
``stage_ms`` and the live ``/metrics`` stage series are the same numbers
by construction.  The histograms are cumulative (Prometheus-style);
:func:`reset` snapshots per-stage baselines and :func:`snapshot_ms`
reports the delta since — a windowed read over shared state, so two
concurrent windowed readers would see each other's time (the bench runs
its regimes serially; live scrapes read the cumulative series instead).

Thread-safe (the H2D put pool and DeviceFeed workers time from their own
threads); overhead is one ``perf_counter`` pair and a histogram update per
*batch*, noise against millisecond-scale stages.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from advanced_scrapper_tpu.obs import telemetry

_lock = threading.Lock()
_hists: dict[str, telemetry.Histogram] = {}
_baseline: dict[str, float] = {}  # per-stage cumulative sum at last reset()

#: canonical stage names (call sites may add others; these are the bench's)
STAGES = ("encode", "h2d", "kernel", "resolve", "matcher_build")


def _hist(stage: str) -> telemetry.Histogram:
    # local cache so the per-batch path skips the registry lock/lookup
    h = _hists.get(stage)
    if h is None:
        h = telemetry.stage_histogram(stage)
        with _lock:
            _hists[stage] = h
    return h


def add(stage: str, seconds: float) -> None:
    _hist(stage).observe(seconds)


@contextmanager
def timed(stage: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        add(stage, time.perf_counter() - t0)


def reset() -> None:
    """Start a measurement window: nothing is cleared (the live series
    stays cumulative); per-stage baselines are snapshotted instead."""
    with _lock:
        _baseline.clear()
        for h in telemetry.stage_histograms():
            _baseline[h.labels["stage"]] = h.sum


def snapshot_ms() -> dict[str, float]:
    """Cumulative per-stage milliseconds since the last :func:`reset`."""
    out: dict[str, float] = {}
    with _lock:
        for h in telemetry.stage_histograms():
            stage = h.labels["stage"]
            out[stage] = round((h.sum - _baseline.get(stage, 0.0)) * 1e3, 1)
    return dict(sorted(out.items()))


def _clear_for_tests() -> None:
    """Drop the handle cache and baselines — required after a test calls
    ``telemetry.REGISTRY.reset()``, or cached handles would keep feeding
    histograms the registry no longer exports."""
    with _lock:
        _hists.clear()
        _baseline.clear()
