"""Device dispatch latency ledger + recompile sentinel (the time domain).

The launch-count ledgers (``obs/stages.py`` device counters) answer "how
many puts and dispatches did a tile cost"; this module answers "where did
dispatch *time* go, and did anything recompile when it shouldn't" — the
two questions the first live-tunnel window needs answered before any
sweep number means anything.

Three always-on instruments (like the stage histograms, they are gated
NUMERICALLY by tier-1 tests and bench deltas, so they exist whether or
not telemetry is enabled):

- **``astpu_dispatch_latency_seconds{kernel, shape[, shard]}``** — one
  observation per device dispatch, labeled by the kernel seam
  (``dedup_fused_tile``, ``matcher_screen_tile``, ``sharded_fused_tile``,
  the legacy parity transports) and the tile shape (``RxW`` — bounded
  cardinality: the chunkers only emit the O(log bs)-per-width prewarmed
  set).  Timing mode is **async-submit** by default: the clock stops when
  the dispatch call returns, i.e. it measures the submission/queueing
  cost on the host (what an async pipeline actually pays per tile; a
  fully-hidden kernel reads ~0, exactly like the ``kernel`` stage
  histogram).  ``ASTPU_DISPATCH_TIMING=fenced`` blocks until the result
  is ready before stopping the clock — ground-truth per-dispatch device
  latency, at the cost of serialising the pipeline (a measurement mode,
  never a production default; the always-on
  ``astpu_dispatch_timing_fenced`` gauge says which mode produced the
  numbers so two runs are never compared across modes unknowingly).
- **``astpu_dispatch_queue_lag_seconds{graph}``** — the staged-pop gap
  through ``pipeline/dispatch.py``: how long a transferred tile sat in
  the staged window before the caller's thread popped it for dispatch.
  Near-zero lag = the dispatch loop is the bottleneck (tiles are
  consumed the moment they land); large lag = H2D runs ahead and the
  window is absorbing it (the dispatch side is the bottleneck).
- **``astpu_jit_compiles_total{kernel}``** — the recompile sentinel:
  :func:`instrument_jit` wraps a jitted step at the builder seams
  (``ops/minhash.py`` / ``ops/match.py`` / ``parallel/sharded_packed.py``
  steps, applied where the pipeline layer fetches them — the ops layer
  never imports obs) and counts jit-cache growth per call.  Prewarm and
  first-corpus compiles are EXPECTED counts; a steady-state increment is
  the exact failure mode prewarm exists to prevent (an unprewarmed shape,
  a silently-changed static arg) — a 44-second stall that used to be
  invisible is now a counted, SLO-alertable event, tier-1-asserted at
  zero across the packed dedup, matcher and sharded planes.
  ``astpu_jit_compile_seconds`` (fed by a ``jax.monitoring`` backend-
  compile listener, installed with the first instrumented step) carries
  the wall-clock of EVERY XLA backend compile in the process — including
  epilogues and steps no seam wraps — so "zero steady-state compiles"
  can be asserted globally, not just per instrumented kernel.  (With
  ``ASTPU_COMPILE_CACHE`` a persistent-cache hit never backend-compiles
  and correctly does not count: a cache load is not a stall.)

Cost model: one ``perf_counter`` pair + a histogram observe per *tile*
dispatch, and two C-level jit-cache-size reads per instrumented call —
noise against millisecond-scale dispatches (regression-gated with the
profiler's overhead test).  This module never imports jax at module
scope (jax-free processes — shard servers, tool parents — import obs
freely); the fenced block and the compile listener import it lazily
inside call paths that only exist when jax is already loaded.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager

from advanced_scrapper_tpu.obs import telemetry

__all__ = [
    "DISPATCH_HISTOGRAM",
    "QUEUE_LAG_HISTOGRAM",
    "JIT_COMPILES",
    "COMPILE_SECONDS",
    "resolve_timing_mode",
    "dispatch_span",
    "queue_lag_histogram",
    "instrument_jit",
    "jit_compiles_total",
    "jit_compiles_by_kernel",
    "compile_seconds_count",
]

DISPATCH_HISTOGRAM = "astpu_dispatch_latency_seconds"
QUEUE_LAG_HISTOGRAM = "astpu_dispatch_queue_lag_seconds"
JIT_COMPILES = "astpu_jit_compiles_total"
COMPILE_SECONDS = "astpu_jit_compile_seconds"

_lock = threading.Lock()
_hists: dict[tuple, telemetry.Histogram] = {}
_lag_hists: dict[str, telemetry.Histogram] = {}
_compile_counters: dict[str, telemetry.Counter] = {}
_listener_installed = False


def resolve_timing_mode() -> str:
    """``"async"`` (default: the clock stops at dispatch-call return) or
    ``"fenced"`` (``ASTPU_DISPATCH_TIMING=fenced``: block-until-ready
    truth).  Read per span — an env lookup per tile, so sweeps can flip
    the mode between runs without re-importing anything."""
    v = os.environ.get("ASTPU_DISPATCH_TIMING", "").strip().lower()
    return "fenced" if v == "fenced" else "async"


def _latency_hist(kernel: str, shape: str, shard: str | None):
    key = (kernel, shape, shard)
    h = _hists.get(key)
    if h is None:
        labels = {"kernel": kernel, "shape": shape}
        if shard is not None:
            labels["shard"] = shard
        h = telemetry.REGISTRY.histogram(
            "astpu_dispatch_latency_seconds",
            "per-dispatch wall clock by kernel/tile-shape (async-submit "
            "timing unless ASTPU_DISPATCH_TIMING=fenced)",
            always=True,
            **labels,
        )
        with _lock:
            _hists[key] = h
    return h


def _mark_timing_mode(mode: str) -> None:
    """Stamp which timing discipline produced the latency numbers (0 =
    async-submit, 1 = fenced) — set on EVERY observation, not just when
    a new series appears, so a mid-run ``ASTPU_DISPATCH_TIMING`` flip on
    a steady shape set is still visible on ``/metrics``.  Cost: one
    gauge set per tile."""
    telemetry.REGISTRY.gauge(
        "astpu_dispatch_timing_fenced",
        "1 = dispatch latency is block-until-ready truth, 0 = "
        "async submission cost",
        always=True,
    ).set(1.0 if mode == "fenced" else 0.0)


class _Span:
    """Mutable result carrier for :func:`dispatch_span` — set ``out`` to
    the dispatch's return value so fenced mode knows what to wait on."""

    __slots__ = ("out",)

    def __init__(self):
        self.out = None


@contextmanager
def dispatch_span(
    kernel: str,
    *,
    rows: int | None = None,
    width: int | None = None,
    shard: int | str | None = None,
    trace: str | None = None,
):
    """Time one device dispatch into the latency ledger.

    ::

        with devprof.dispatch_span("dedup_fused_tile", rows=r, width=w) as sp:
            out = step(running, dev, ...)
            sp.out = out

    Only successful dispatches are observed (an OOM-backoff retry must
    not pollute the distribution with its failed parent attempt).  Under
    ``ASTPU_DISPATCH_TIMING=fenced`` the exit blocks on ``sp.out`` before
    stopping the clock.  ``trace`` attaches a slow-call exemplar when the
    observation lands in the histogram's top bucket.
    """
    span = _Span()
    shape = f"{rows}x{width}" if rows is not None and width is not None else ""
    t0 = time.perf_counter()
    ok = False
    try:
        yield span
        ok = True
    finally:
        if ok:
            mode = resolve_timing_mode()
            if mode == "fenced" and span.out is not None:
                # a DEVICE error surfacing at the fence propagates (the
                # dispatch failed, just asynchronously — observing it
                # would pollute the distribution with the OOM ladder's
                # failed parent attempts); only a non-jax/non-array
                # result (tests) falls back to async timing
                try:
                    import jax

                    jax.block_until_ready(span.out)
                except (ImportError, TypeError, AttributeError):
                    pass
            _mark_timing_mode(mode)
            _latency_hist(
                kernel, shape, None if shard is None else str(shard)
            ).observe(time.perf_counter() - t0, trace=trace)


def queue_lag_histogram(graph: str) -> telemetry.Histogram:
    """The staged-pop lag series for one dispatch graph (always-on;
    ``pipeline/dispatch.py`` stamps tiles as the put pool stages them and
    observes the gap when the caller pops)."""
    h = _lag_hists.get(graph)
    if h is None:
        h = telemetry.REGISTRY.histogram(
            "astpu_dispatch_queue_lag_seconds",
            "staged-tile wait between h2d completion and the caller's "
            "dispatch pop (pipeline/dispatch.py staged window)",
            always=True,
            graph=graph,
        )
        with _lock:
            _lag_hists[graph] = h
    return h


# -- recompile sentinel -------------------------------------------------------


def _compiles(kernel: str) -> telemetry.Counter:
    c = _compile_counters.get(kernel)
    if c is None:
        c = telemetry.REGISTRY.counter(
            "astpu_jit_compiles_total",
            "jit-cache compiles per instrumented kernel seam (steady "
            "state must stay flat — prewarm exists to front-load these)",
            always=True,
            kernel=kernel,
        )
        with _lock:
            _compile_counters[kernel] = c
    return c


def _install_compile_listener() -> None:
    """Feed every XLA *backend* compile's duration into the always-on
    ``astpu_jit_compile_seconds`` histogram via ``jax.monitoring`` —
    installed once, with the first instrumented step (so jax is already
    importable there).  The handle is looked up per event, not cached:
    compiles are rare, and a registry reset (tests) must not orphan it.
    """
    global _listener_installed
    with _lock:
        if _listener_installed:
            return
        _listener_installed = True
    try:
        from jax import monitoring
    except Exception:
        return

    def _on_duration(name: str, value: float, **_kw) -> None:
        if not name.endswith("backend_compile_duration"):
            return
        try:
            telemetry.REGISTRY.histogram(
                "astpu_jit_compile_seconds",
                "wall clock of every XLA backend compile in this process "
                "(persistent-cache hits do not compile and do not count)",
                always=True,
            ).observe(float(value))
        except Exception:
            pass  # a metrics fault must never break a compile

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        pass


def instrument_jit(fn, kernel: str):
    """Wrap a jitted step so every jit-cache miss is counted on the
    always-on ``astpu_jit_compiles_total{kernel}`` sentinel.

    Applied at the pipeline layer where the ``ops``/``parallel`` step
    builders' results are fetched and cached (the builders themselves may
    not import obs — layering).  The wrapper is transparent: same call
    surface, and ``_cache_size`` passes through so prewarm-set gate tests
    keep asserting on it.  A non-jit callable (or a jax too old to expose
    ``_cache_size``) passes through unwrapped — the sentinel degrades to
    the global compile histogram, never to an error.

    Concurrency note: the before/after cache-size read pair is not
    atomic across threads; two threads compiling the same kernel
    concurrently may attribute both compiles to one call.  The TOTAL per
    kernel stays exact (cache size is monotone), which is what the
    steady-state-zero assertion needs.
    """
    cache_size = getattr(fn, "_cache_size", None)
    if cache_size is None:
        return fn
    _install_compile_listener()

    def wrapped(*args, **kwargs):
        before = cache_size()
        out = fn(*args, **kwargs)
        grew = cache_size() - before
        if grew > 0:
            _compiles(kernel).inc(grew)
            from advanced_scrapper_tpu.obs import trace

            trace.record("event", "jit.compile", kernel=kernel, n=int(grew))
        return out

    wrapped.__name__ = getattr(fn, "__name__", kernel)
    wrapped.__qualname__ = getattr(fn, "__qualname__", kernel)
    wrapped.__wrapped__ = fn
    wrapped._cache_size = cache_size
    wrapped._sentinel_kernel = kernel
    return wrapped


# -- windowed reads -----------------------------------------------------------


def jit_compiles_by_kernel() -> dict[str, float]:
    """Cumulative sentinel counts per kernel label (subtract two
    snapshots to window a regime — bench does)."""
    out: dict[str, float] = {}
    for c in telemetry.REGISTRY.find(JIT_COMPILES):
        k = c.labels.get("kernel", "")
        out[k] = out.get(k, 0.0) + c.value
    return out


def jit_compiles_total() -> float:
    """Cumulative sentinel count across every instrumented kernel."""
    return sum(jit_compiles_by_kernel().values())


def compile_seconds_count() -> tuple[int, float]:
    """``(count, sum_seconds)`` of the global backend-compile histogram —
    the catch-everything half of the steady-state-zero assertion."""
    n, s = 0, 0.0
    for h in telemetry.REGISTRY.find(COMPILE_SECONDS):
        n += h.count
        s += h.sum
    return n, s


def _clear_for_tests() -> None:
    """Registry-reset hook: drop cached handles so a reset never leaves
    orphaned series being fed outside the registry's view (the
    obs/stages.py lesson)."""
    with _lock:
        _hists.clear()
        _lag_hists.clear()
        _compile_counters.clear()


telemetry.REGISTRY.add_reset_hook(_clear_for_tests)
