"""Process-wide metrics registry: counters, gauges, log-bucketed histograms.

The pipeline was operated blind beyond the scraper's 10 Hz stats line —
four disconnected seeds (``obs/stages.py`` call-site counters, the orphaned
``StepTimer``, the scraper-local ``StatsTracker``, bench-only ``stage_ms``)
with no common export surface.  This module is the one source of truth they
all now feed:

- :class:`Counter` / :class:`Gauge` / :class:`Histogram` — thread-safe
  metric handles.  Histograms are log₂-bucketed latency distributions
  (~1 µs … 64 s) with p50/p95/p99 estimation; one lock + a bucket
  increment per observation, noise against millisecond-scale batches.
- :class:`Registry` — names metric handles (with optional labels), renders
  them as Prometheus text (``/metrics``) and a JSON snapshot (``/status``),
  and hosts *callback gauges*: zero hot-path-cost gauges read live at
  scrape time (queue depth, arena occupancy, lease fleet state), held via
  weakref so transient owners (a ``DeviceFeed`` per stream) never leak.
- :class:`StatusServer` — a tiny stdlib HTTP exporter serving ``GET
  /metrics`` + ``GET /status``; the same two endpoints also ride the
  existing control-plane server (``net/control.py``) and the lease server
  (``net/lease.py``).

Cost model: telemetry is OFF by default (``ASTPU_TELEMETRY=1`` enables).
Disabled, the factory methods hand back shared no-op singletons — a call
site's per-batch cost is one attribute call, no lock, no allocation
(regression-tested).  Two families bypass the gate because they predate
this layer and are already priced into the hot paths: *stage histograms*
(``always=True`` — ``obs/stages.py`` is a thin view over them, so bench
``stage_ms`` and live ``/metrics`` can never disagree) and *event
counters* for rare faults (quarantines, chaos injections, rate-limit
trips), whose firing is by definition off the fast path.

Metric naming scheme: ``astpu_<layer>_<what>[_total|_seconds|_bytes]`` —
``layer`` ∈ feed, dedup, matcher, scraper, lease, fault, quarantine, stage.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import weakref

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "StatusServer",
    "REGISTRY",
    "NOOP",
    "enabled",
    "set_enabled",
    "counter",
    "gauge",
    "histogram",
    "gauge_fn",
    "event_counter",
    "stage_histogram",
    "stage_histograms",
    "register_process_metrics",
    "serve_metrics",
    "serve_status",
    "send_http_payload",
    "PROMETHEUS_CONTENT_TYPE",
]

_TRUTHY = ("1", "true", "yes", "on")


class _Noop:
    """Shared do-nothing metric handle — what call sites get when telemetry
    is disabled.  No lock, no state: the disabled hot path is one attribute
    call per event."""

    __slots__ = ()

    def inc(self, n=1):
        pass

    def dec(self, n=1):
        pass

    def set(self, v):
        pass

    def observe(self, v, trace=None):
        pass

    @property
    def value(self):
        return 0.0

    @property
    def exemplar(self):
        return None


NOOP = _Noop()


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    kind = "counter"
    __slots__ = ("name", "labels", "help", "_lock", "_value")

    def __init__(self, name: str, labels: dict, help: str = ""):
        self.name = name
        self.labels = dict(labels)
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    kind = "gauge"
    __slots__ = ("name", "labels", "help", "_lock", "_value")

    def __init__(self, name: str, labels: dict, help: str = ""):
        self.name = name
        self.labels = dict(labels)
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


#: log₂ bucket upper bounds in seconds: 2⁻²⁰ (~1 µs) … 2⁶ (64 s).  Base-2 so
#: the bucket of an observation falls out of one ``math.frexp`` — no search.
_BUCKET_LO_EXP = -20
_BUCKET_HI_EXP = 6
BUCKET_BOUNDS = tuple(2.0**e for e in range(_BUCKET_LO_EXP, _BUCKET_HI_EXP + 1))


class Histogram:
    """Log-bucketed distribution (latencies in seconds by convention).

    Cumulative, Prometheus-style: ``sum``/``count`` grow forever; views
    that need a window (bench ``stage_ms``) snapshot-and-subtract.
    """

    kind = "histogram"
    __slots__ = (
        "name", "labels", "help", "_lock", "_buckets", "_sum", "_count",
        "_max_bucket", "_exemplar",
    )

    def __init__(self, name: str, labels: dict, help: str = ""):
        self.name = name
        self.labels = dict(labels)
        self.help = help
        self._lock = threading.Lock()
        # one slot per bound + overflow (+Inf)
        self._buckets = [0] * (len(BUCKET_BOUNDS) + 1)
        self._sum = 0.0
        self._count = 0
        self._max_bucket = -1       # highest occupied bucket index so far
        self._exemplar: dict | None = None  # slow-call exemplar (see observe)

    @staticmethod
    def _bucket_index(v: float) -> int:
        if v <= BUCKET_BOUNDS[0]:
            return 0
        m, e = math.frexp(v)  # v = m · 2^e, 0.5 ≤ m < 1
        if m == 0.5:  # exact powers of two belong in their own bucket
            e -= 1
        i = e - _BUCKET_LO_EXP
        return i if i < len(BUCKET_BOUNDS) else len(BUCKET_BOUNDS)

    def observe(self, v: float, trace: str | None = None) -> None:
        """Record one observation.  ``trace`` attaches a *slow-call
        exemplar*: when the observation lands in (or above) the highest
        bucket this histogram has ever occupied — i.e. it is one of the
        p99-tail outliers — the trace id is kept as the series' exemplar,
        so a dashboard can jump from "p99 spiked" straight to the one
        stitched trace that caused it.  O(1), one compare on the hot
        path."""
        i = self._bucket_index(v)
        with self._lock:
            self._buckets[i] += 1
            self._sum += v
            self._count += 1
            if i >= self._max_bucket:
                self._max_bucket = i
                if trace is not None:
                    self._exemplar = {
                        "trace": trace,
                        "value": v,
                        "ts": time.time(),
                    }

    @property
    def exemplar(self) -> dict | None:
        """The current slow-call exemplar (``{trace, value, ts}``) or None."""
        with self._lock:
            return dict(self._exemplar) if self._exemplar else None

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def state(self) -> tuple[list[int], float, int]:
        with self._lock:
            return list(self._buckets), self._sum, self._count

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]) by linear interpolation inside
        the containing bucket; 0.0 when empty."""
        buckets, _s, count = self.state()
        if count == 0:
            return 0.0
        target = q * count
        cum = 0
        for i, n in enumerate(buckets):
            if n == 0:
                continue
            if cum + n >= target:
                lo = 0.0 if i == 0 else BUCKET_BOUNDS[i - 1]
                hi = (
                    BUCKET_BOUNDS[i]
                    if i < len(BUCKET_BOUNDS)
                    else BUCKET_BOUNDS[-1] * 2
                )
                frac = (target - cum) / n
                return lo + (hi - lo) * frac
            cum += n
        return BUCKET_BOUNDS[-1] * 2

    def percentiles_ms(self) -> dict[str, float]:
        return {
            "p50_ms": round(self.percentile(0.50) * 1e3, 3),
            "p95_ms": round(self.percentile(0.95) * 1e3, 3),
            "p99_ms": round(self.percentile(0.99) * 1e3, 3),
        }


class _CallbackGauge:
    """Deferred gauge: ``fn(owner)`` is evaluated at scrape time, the owner
    held by weakref so registration never extends its lifetime.  ``fn`` may
    return a number, or (with ``expand``) a ``{label_value: number}`` dict
    that fans out into one series per key."""

    kind = "gauge"
    __slots__ = ("name", "labels", "help", "expand", "_fn", "_owner")

    def __init__(self, name, labels, fn, owner, expand, help=""):
        self.name = name
        self.labels = dict(labels)
        self.help = help
        self.expand = expand
        self._fn = fn
        self._owner = weakref.ref(owner) if owner is not None else None

    def samples(self):
        """``[(labels, value)]`` or None when the owner died / fn failed."""
        owner = None
        if self._owner is not None:
            owner = self._owner()
            if owner is None:
                return None
        try:
            v = self._fn(owner) if self._owner is not None else self._fn()
        except Exception:
            return []
        if self.expand is not None and isinstance(v, dict):
            return [
                ({**self.labels, self.expand: str(k)}, float(val))
                for k, val in sorted(v.items(), key=lambda kv: str(kv[0]))
            ]
        return [(self.labels, float(v))]


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_bound(b: float) -> str:
    return format(b, ".9g")


class Registry:
    """Thread-safe named-metric store + exporter."""

    def __init__(self, enabled: bool | None = None):
        self._lock = threading.Lock()
        self._metrics: dict[tuple, object] = {}
        self._callbacks: dict[tuple, _CallbackGauge] = {}
        self._enabled = enabled  # None → resolve from ASTPU_TELEMETRY lazily
        self._reset_hooks: list = []  # see reset(): handle-cache droppers
        #: bumped by reset(): handle-caching instrumenters (the admission
        #: plane) compare it lazily and re-instrument on first use after
        #: a reset — dormant objects never pollute a fresh registry
        self.generation = 0

    # -- gating ------------------------------------------------------------

    def enabled(self) -> bool:
        if self._enabled is None:
            self._enabled = (
                os.environ.get("ASTPU_TELEMETRY", "").lower() in _TRUTHY
            )
        return self._enabled

    def set_enabled(self, on: bool | None) -> None:
        """Force the gate (tests); ``None`` re-reads ``ASTPU_TELEMETRY`` at
        next use.  Affects handles created AFTER the call — call sites
        fetch handles at construction time."""
        self._enabled = on

    # -- factories ---------------------------------------------------------

    def _get(self, cls, name, labels, help, always):
        if not (always or self.enabled()):
            return NOOP
        key = (name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, labels, help)
                self._metrics[key] = m
            return m

    def counter(self, name: str, help: str = "", always: bool = False, **labels):
        return self._get(Counter, name, labels, help, always)

    def gauge(self, name: str, help: str = "", always: bool = False, **labels):
        return self._get(Gauge, name, labels, help, always)

    def histogram(self, name: str, help: str = "", always: bool = False, **labels):
        return self._get(Histogram, name, labels, help, always)

    def gauge_fn(
        self,
        name: str,
        fn,
        *,
        owner=None,
        expand: str | None = None,
        help: str = "",
        always: bool = False,
        **labels,
    ) -> None:
        """Register a scrape-time callback gauge.  With ``owner``, ``fn`` is
        called as ``fn(owner)`` and the owner is weakref'd (a dead owner
        unregisters the gauge); re-registering the same (name, labels)
        replaces the previous callback."""
        if not (always or self.enabled()):
            return
        key = (name, _label_key(labels))
        cb = _CallbackGauge(name, labels, fn, owner, expand, help)
        with self._lock:
            self._callbacks[key] = cb

    # -- introspection -----------------------------------------------------

    def find(self, name: str) -> list:
        """Live (non-callback) metrics registered under ``name``."""
        with self._lock:
            return [m for (n, _), m in sorted(self._metrics.items()) if n == name]

    def _collect(self):
        """``(stored_metrics, callback_samples)`` with dead callbacks swept."""
        with self._lock:
            metrics = [m for _, m in sorted(self._metrics.items())]
            callbacks = list(self._callbacks.items())
        samples = []
        dead = []
        for key, cb in callbacks:
            s = cb.samples()
            if s is None:
                dead.append((key, cb))
                continue
            for labels, v in s:
                samples.append((cb.name, labels, v, cb.help))
        if dead:
            with self._lock:
                for key, cb in dead:
                    # identity check: a replacement registered between the
                    # snapshot and this sweep must not be swept with its
                    # dead predecessor
                    if self._callbacks.get(key) is cb:
                        del self._callbacks[key]
        return metrics, samples

    # -- exporters ---------------------------------------------------------

    def prometheus_text(self) -> str:
        """The full registry in Prometheus text exposition format."""
        metrics, cb_samples = self._collect()
        lines: list[str] = []
        typed: set[str] = set()

        def head(name: str, kind: str, help: str) -> None:
            if name in typed:
                return
            typed.add(name)
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} {kind}")

        for m in metrics:
            head(m.name, m.kind, m.help)
            if m.kind == "histogram":
                buckets, total, count = m.state()
                cum = 0
                for i, n in enumerate(buckets[:-1]):
                    cum += n
                    lab = _fmt_labels({**m.labels, "le": _fmt_bound(BUCKET_BOUNDS[i])})
                    lines.append(f"{m.name}_bucket{lab} {cum}")
                cum += buckets[-1]
                lab = _fmt_labels({**m.labels, "le": "+Inf"})
                lines.append(f"{m.name}_bucket{lab} {cum}")
                lines.append(f"{m.name}_sum{_fmt_labels(m.labels)} {repr(total)}")
                lines.append(f"{m.name}_count{_fmt_labels(m.labels)} {count}")
                ex = m.exemplar
                if ex is not None:
                    # comment line (not OpenMetrics exemplar syntax): every
                    # Prometheus text parser skips it, and the collector's
                    # parser picks it back up to stitch fleet-wide
                    lines.append(
                        f"# exemplar {m.name}{_fmt_labels(m.labels)} "
                        f'trace="{ex["trace"]}" value={repr(ex["value"])} '
                        f"ts={repr(ex['ts'])}"
                    )
            else:
                lines.append(
                    f"{m.name}{_fmt_labels(m.labels)} {_fmt_value(m.value)}"
                )
        for name, labels, v, help in cb_samples:
            head(name, "gauge", help)
            lines.append(f"{name}{_fmt_labels(labels)} {_fmt_value(v)}")
        return "\n".join(lines) + "\n"

    def status(self) -> dict:
        """JSON-able snapshot for ``/status``: one entry per series, with
        p50/p95/p99 attached to histograms."""
        metrics, cb_samples = self._collect()
        out = []
        for m in metrics:
            entry = {"name": m.name, "kind": m.kind, "labels": m.labels}
            if m.kind == "histogram":
                _b, total, count = m.state()
                entry["count"] = count
                entry["sum"] = total
                entry.update(m.percentiles_ms())
                ex = m.exemplar
                if ex is not None:
                    entry["exemplar"] = ex
            else:
                entry["value"] = m.value
            out.append(entry)
        for name, labels, v, _help in cb_samples:
            out.append({"name": name, "kind": "gauge", "labels": labels, "value": v})
        return {"ts": time.time(), "pid": os.getpid(), "metrics": out}

    def reset(self) -> None:
        """Drop every metric and callback (tests only — production metrics
        are cumulative for the life of the process).  Modules that cache
        metric HANDLES (``obs/stages.py``'s always-on device counters)
        register a reset hook so their caches drop with the registry —
        otherwise a reset orphans the cached objects and later
        increments land outside :meth:`find`'s view (a real test-ordering
        bug this hook retired)."""
        with self._lock:
            self._metrics.clear()
            self._callbacks.clear()
            self.generation += 1
        keep = []
        for fn in list(self._reset_hooks):
            try:
                # a hook returning False unregisters itself (how
                # per-instance hooks — a dead AdmissionController's
                # re-instrumenter — avoid accumulating forever)
                if fn() is not False:
                    keep.append(fn)
            except Exception:
                keep.append(fn)
        self._reset_hooks = keep

    def add_reset_hook(self, fn) -> None:
        self._reset_hooks.append(fn)


#: the process-wide registry every layer instruments against
REGISTRY = Registry()


def enabled() -> bool:
    return REGISTRY.enabled()


def set_enabled(on: bool | None) -> None:
    REGISTRY.set_enabled(on)


def counter(name: str, help: str = "", **labels):
    return REGISTRY.counter(name, help, **labels)


def gauge(name: str, help: str = "", **labels):
    return REGISTRY.gauge(name, help, **labels)


def histogram(name: str, help: str = "", **labels):
    return REGISTRY.histogram(name, help, **labels)


def gauge_fn(name: str, fn, **kw) -> None:
    REGISTRY.gauge_fn(name, fn, **kw)


def event_counter(name: str, help: str = "", **labels):
    """Always-on counter for RARE events (quarantines, fault injections,
    rate-limit trips): firing is off the fast path by definition, and the
    counts must be visible on ``/metrics`` whenever anything exports."""
    return REGISTRY.counter(name, help, always=True, **labels)


#: stage histograms — the one source of truth behind ``obs/stages.py`` AND
#: the live ``/metrics`` stage series (``always`` because stage timing
#: predates this layer and bench's stage_ms depends on it unconditionally)
STAGE_HISTOGRAM = "astpu_stage_seconds"


def stage_histogram(stage: str) -> Histogram:
    return REGISTRY.histogram(
        STAGE_HISTOGRAM,
        "per-stage wall clock (call-site attribution; obs/stages.py)",
        always=True,
        stage=stage,
    )


def stage_histograms() -> list[Histogram]:
    return REGISTRY.find(STAGE_HISTOGRAM)


def register_process_metrics(registry: Registry | None = None) -> None:
    """Standard process-health gauges (RSS, CPU seconds, uptime, thread
    count) — registered by exporters at start so even a quiet pipeline
    serves a meaningful ``/metrics``.  Idempotent (same keys replace)."""
    import resource
    import sys

    reg = registry or REGISTRY
    t0 = time.time()
    # ru_maxrss is KiB on Linux/BSD but BYTES on macOS
    rss_scale = 1 if sys.platform == "darwin" else 1024

    reg.gauge_fn(
        "astpu_process_uptime_seconds",
        lambda: time.time() - t0,
        always=True,
        help="seconds since process metrics were registered",
    )
    reg.gauge_fn(
        "astpu_process_max_rss_bytes",
        lambda: resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * rss_scale,
        always=True,
        help="peak resident set size",
    )
    reg.gauge_fn(
        "astpu_process_cpu_seconds",
        lambda: (
            resource.getrusage(resource.RUSAGE_SELF).ru_utime
            + resource.getrusage(resource.RUSAGE_SELF).ru_stime
        ),
        always=True,
        help="user+system CPU time consumed",
    )
    reg.gauge_fn(
        "astpu_process_threads",
        lambda: threading.active_count(),
        always=True,
        help="live Python threads",
    )


# -- HTTP export ------------------------------------------------------------

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4"


def send_http_payload(handler, code: int, body: bytes, ctype: str) -> None:
    """One HTTP response on a ``BaseHTTPRequestHandler``, swallowing client
    disconnects — a scraper hanging up mid-``/metrics`` must not dump a
    traceback from the server thread."""
    try:
        handler.send_response(code)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
    except (BrokenPipeError, ConnectionResetError):
        pass


def serve_metrics(handler, registry: Registry | None = None) -> None:
    """``GET /metrics`` body — the ONE implementation every exporter
    (StatusServer, the control plane) mounts."""
    reg = registry or REGISTRY
    send_http_payload(
        handler, 200, reg.prometheus_text().encode("utf-8"),
        PROMETHEUS_CONTENT_TYPE,
    )


def serve_status(handler, registry: Registry | None = None, extra_status=None) -> None:
    """``GET /status`` body; ``extra_status()``'s dict merges into the
    payload (a failing callback degrades to an error field, never a 500)."""
    reg = registry or REGISTRY
    payload = reg.status()
    if extra_status is not None:
        try:
            payload.update(extra_status())
        except Exception as e:
            payload["extra_status_error"] = str(e)
    send_http_payload(
        handler, 200, json.dumps(payload).encode("utf-8"), "application/json"
    )


class StatusServer:
    """Minimal stdlib exporter: ``GET /metrics`` (Prometheus text) and
    ``GET /status`` (JSON).  Rides beside servers that aren't HTTP (the
    lease plane) and inside processes that have no server at all (bench).

    ``extra_status`` is an optional zero-arg callable whose dict is merged
    into the ``/status`` payload under its own keys (e.g. the lease
    server's fleet view).
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        registry: Registry | None = None,
        extra_status=None,
        name: str | None = None,
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry or REGISTRY
        register_process_metrics(reg)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    serve_metrics(self, reg)
                elif self.path == "/status":
                    serve_status(self, reg, extra_status)
                elif self.path == "/profile":
                    # the continuous host profiler's folded stacks
                    # (obs/profiler.py; 200 with a comment line when
                    # ASTPU_PROFILE is unset) — lazy import: profiler
                    # imports telemetry at module scope
                    from advanced_scrapper_tpu.obs import profiler

                    profiler.serve_profile(self)
                else:
                    send_http_payload(
                        self,
                        404,
                        json.dumps(
                            {"error": f"no such endpoint {self.path}"}
                        ).encode("utf-8"),
                        "application/json",
                    )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self.name = name or f"pid{os.getpid()}"
        self._thread: threading.Thread | None = None
        self._endpoint_file: str | None = None

    def start(self) -> "StatusServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        # ASTPU_PROFILE=<hz>: any process that exports metrics also
        # profiles itself — the sampler is process-global and idempotent,
        # and /profile (above) serves its folded stacks
        from advanced_scrapper_tpu.obs import profiler

        profiler.maybe_start_global()
        # fleet discovery: under ASTPU_OBS_DIR every exporter announces
        # its endpoint as a one-line file the metrics collector
        # (obs/collector.py) watches — no port registry, no race against
        # ephemeral binds (the file appears only after listen succeeded)
        obs_dir = os.environ.get("ASTPU_OBS_DIR")
        if obs_dir:
            try:
                self.announce(obs_dir)
            except OSError:
                pass  # discovery is best-effort, serving is not
        return self

    def announce(self, obs_dir: str, name: str | None = None) -> str:
        """Write ``<obs_dir>/<name>.endpoint`` containing this server's
        base url, atomically (tmp + rename) so a concurrently-scanning
        collector never reads a half-written line.  Returns the path."""
        name = name or self.name
        os.makedirs(obs_dir, exist_ok=True)
        path = os.path.join(obs_dir, f"{name}.endpoint")
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(f"http://{self.host}:{self.port}\n")
        os.replace(tmp, path)
        self._endpoint_file = path
        return path

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._endpoint_file is not None:
            try:
                os.unlink(self._endpoint_file)
            except OSError:
                pass
            self._endpoint_file = None
