"""Sliding-window throughput statistics.

Same observable surface as the reference's ``StatsTracker``
(``constant_rate_scrapper.py:44-104``): success/fail counts over a rolling
window, actual request rate, cumulative totals.  Implementation differs —
timestamps live in ``deque``\\ s pruned from the left (the reference rebuilds
whole lists on every read) and the window length is injected instead of read
from a module global.  The server-side request/response variant
(``server1.py:26-52``) is :class:`RateStats`.
"""

from __future__ import annotations

import threading
import time
from collections import deque


class StatsTracker:
    def __init__(self, window: float = 10.0, clock=time.time):
        self._window = window
        self._clock = clock
        self._lock = threading.Lock()
        self._success: deque[float] = deque()
        self._fail: deque[float] = deque()
        self._requests: deque[float] = deque()
        self.cumulative_success = 0
        self.cumulative_fail = 0

    def _prune(self, now: float) -> None:
        cutoff = now - self._window
        for dq in (self._success, self._fail, self._requests):
            while dq and dq[0] < cutoff:
                dq.popleft()

    def record_success(self) -> None:
        with self._lock:
            now = self._clock()
            self._success.append(now)
            self._requests.append(now)
            self.cumulative_success += 1

    def record_fail(self) -> None:
        with self._lock:
            now = self._clock()
            self._fail.append(now)
            self._requests.append(now)
            self.cumulative_fail += 1

    def get_stats(self) -> tuple[int, int]:
        """(successes, failures) inside the window."""
        with self._lock:
            self._prune(self._clock())
            return len(self._success), len(self._fail)

    def get_actual_rate(self) -> float:
        """Requests/second over the window (0.0 when idle) — same definition
        as the reference (count / span since oldest request, :85-100)."""
        with self._lock:
            now = self._clock()
            self._prune(now)
            if not self._requests:
                return 0.0
            span = now - self._requests[0]
            return len(self._requests) / span if span > 0 else float(len(self._requests))

    def get_cumulative_stats(self) -> tuple[int, int]:
        with self._lock:
            return self.cumulative_success, self.cumulative_fail


class RateStats:
    """Request/response rate pair (successor of ``server1.py:26-52``)."""

    def __init__(self, window: float = 10.0, clock=time.time):
        self.requests = StatsTracker(window, clock)
        self.responses = StatsTracker(window, clock)

    def record_request(self) -> None:
        self.requests.record_success()

    def record_response(self) -> None:
        self.responses.record_success()

    def rates(self) -> tuple[float, float]:
        return self.requests.get_actual_rate(), self.responses.get_actual_rate()
