"""Profiling hooks: step timing, XLA trace export, continuous host profiler.

The reference's only performance observability is the 10 Hz stats line
(SURVEY.md §5.1); the TPU framework adds what that can't see — device step
latency percentiles, ``jax.profiler`` traces for the kernel timeline, and
(since the time-domain plane) a **continuous all-threads stack sampler**:

- :class:`StackSampler` — an N-Hz daemon thread walking
  ``sys._current_frames()`` and aggregating every thread's stack into
  *folded-stack* form (``root;frame;leaf count`` — the flamegraph input
  format), with its own overhead accounted (:meth:`overhead_ratio` is a
  measured number, regression-gated <1% in tier-1, not a promise);
- ``ASTPU_PROFILE=<hz>`` starts ONE process-global sampler the first time
  an exporter comes up (``telemetry.StatusServer`` / the shard sidecars),
  and every exporter then serves its output as ``GET /profile`` — which
  the fleet collector (``obs/collector.py``) harvests into one merged
  per-instance view and ``obs_top --prof`` renders.

Sampling is statistical truth, not a tracer: a stack's count divided by
total samples is the fraction of wall time that stack owned.  Cost per
pass is one ``_current_frames`` snapshot + dict increments (frame labels
are memoised per code object), so the budget scales with hz × thread
count; the default 19 Hz is deliberately off the 10/20 Hz beat of the
stats lines it profiles.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from collections import deque

__all__ = [
    "StepTimer",
    "xla_trace",
    "StackSampler",
    "resolve_profile_hz",
    "maybe_start_global",
    "ensure_global",
    "global_sampler",
    "stop_global",
    "profile_response_text",
    "serve_profile",
]


class StepTimer:
    """Rolling per-step duration tracker (device batches, host stages).

    Production step loops own one (``pipeline.feed.DeviceFeed.timer``,
    ``pipeline.dedup.NearDupEngine.step_timer``) so :meth:`summary` is
    reachable live, and each observation can mirror into a telemetry
    histogram (``histogram=``) so the same steps show on ``/metrics`` —
    the registry hands back a no-op handle when telemetry is disabled,
    keeping the mirrored path free.  Appends are deque ops (thread-safe
    under the GIL); :meth:`summary` reads a snapshot.
    """

    def __init__(self, maxlen: int = 512, histogram=None):
        self._durations: deque[float] = deque(maxlen=maxlen)
        self._items: deque[int] = deque(maxlen=maxlen)
        self._histogram = histogram

    @contextlib.contextmanager
    def step(self, n_items: int = 1):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(time.perf_counter() - t0, n_items)

    def add(self, seconds: float, n_items: int = 1) -> None:
        """Record a step timed by the caller — for loops where the item
        count is only known after the work (e.g. a pop that may drain a
        partial tile)."""
        self._durations.append(seconds)
        self._items.append(n_items)
        if self._histogram is not None:
            self._histogram.observe(seconds)

    def summary(self) -> dict:
        if not self._durations:
            return {"steps": 0}
        ds = sorted(self._durations)
        total_t = sum(ds)
        total_n = sum(self._items)
        return {
            "steps": len(ds),
            "p50_ms": round(ds[len(ds) // 2] * 1e3, 3),
            "p95_ms": round(ds[int(len(ds) * 0.95)] * 1e3, 3),
            "items_per_sec": round(total_n / total_t, 1) if total_t > 0 else 0.0,
        }


@contextlib.contextmanager
def xla_trace(log_dir: str | None):
    """``jax.profiler.trace`` wrapper; no-op when ``log_dir`` is falsy."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


# -- continuous host profiler -------------------------------------------------

DEFAULT_HZ = 19.0
#: distinct-stack cap: a pathological workload (deep recursion with
#: varying shapes) must not grow the fold table without bound — overflow
#: collapses into one honest bucket instead of evicting silently
MAX_STACKS = 4096
OVERFLOW_KEY = "_overflow_"


class StackSampler:
    """N-Hz all-threads stack sampler aggregating folded stacks.

    ``hz`` is the target sampling rate; ``maxdepth`` bounds the walked
    frames per thread (deepest frames kept — the leaf is what names the
    hot code).  The sampler accounts its own busy time: the tier-1
    overhead gate asserts :meth:`overhead_ratio` stays under 1% on the
    ragged dedup regime, so "continuous" is a measured claim.

    Telemetry (always-on — the sampler only exists because an operator
    set ``ASTPU_PROFILE``): ``astpu_prof_samples_total`` passes,
    ``astpu_prof_sample_seconds`` per-pass cost, plus live callback
    gauges ``astpu_prof_stacks`` / ``astpu_prof_overhead_ratio`` /
    ``astpu_prof_hz``.
    """

    def __init__(self, hz: float = DEFAULT_HZ, *, maxdepth: int = 64):
        self.hz = max(0.1, float(hz))
        self.maxdepth = maxdepth
        self._counts: dict[str, int] = {}
        self._label_cache: dict[int, str] = {}  # id(code) → "file:func"
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._samples = 0
        self._busy_s = 0.0
        self._started_mono: float | None = None
        self._started_ts: float | None = None

        self._instrument()
        # a registry reset (tests) must not leave a LIVE sampler feeding
        # orphaned handles invisible to /metrics — re-instrument lazily,
        # self-unregistering once this sampler is gone (the obs/stages
        # reset-hook lesson, per-instance flavor)
        import weakref

        from advanced_scrapper_tpu.obs import telemetry

        ref = weakref.ref(self)

        def _reinstrument():
            s = ref()
            if s is None:
                return False  # unregister the hook with its sampler
            s._instrument()
            return True

        telemetry.REGISTRY.add_reset_hook(_reinstrument)

    def _instrument(self) -> None:
        """(Re-)fetch the sampler's metric handles from the CURRENT
        registry generation; called at init and from the reset hook."""
        from advanced_scrapper_tpu.obs import telemetry

        self._m_samples = telemetry.REGISTRY.counter(
            "astpu_prof_samples_total",
            "stack-sampler passes taken (all threads per pass)",
            always=True,
        )
        self._m_pass = telemetry.REGISTRY.histogram(
            "astpu_prof_sample_seconds",
            "cost of one sampling pass (the overhead numerator)",
            always=True,
        )
        telemetry.REGISTRY.gauge_fn(
            "astpu_prof_stacks",
            lambda s: float(len(s._counts)),
            owner=self,
            always=True,
            help="distinct folded stacks held by the sampler",
        )
        telemetry.REGISTRY.gauge_fn(
            "astpu_prof_overhead_ratio",
            lambda s: s.overhead_ratio(),
            owner=self,
            always=True,
            help="measured sampler busy fraction of wall time (<0.01 gated)",
        )
        telemetry.REGISTRY.gauge_fn(
            "astpu_prof_hz",
            lambda s: s.hz,
            owner=self,
            always=True,
            help="configured sampling rate",
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "StackSampler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._started_mono = time.monotonic()
        self._started_ts = time.time()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="astpu-prof-sampler"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- sampling ----------------------------------------------------------

    def _label(self, code) -> str:
        key = id(code)
        lab = self._label_cache.get(key)
        if lab is None:
            fn = code.co_filename
            base = os.path.basename(fn)
            if base.endswith(".py"):
                base = base[:-3]
            lab = f"{base}:{code.co_name}"
            if len(self._label_cache) < 65536:  # id-reuse is harmless here
                self._label_cache[key] = lab
        return lab

    def sample_once(self) -> int:
        """One pass over every live thread; returns stacks folded.  The
        sampler's own thread is skipped (profiling the profiler would put
        a constant artifact at the top of every report)."""
        t0 = time.perf_counter()
        me = threading.get_ident()
        frames = sys._current_frames()
        folded = 0
        with self._lock:
            for tid, frame in frames.items():
                if tid == me:
                    continue
                parts: list[str] = []
                depth = 0
                while frame is not None and depth < self.maxdepth:
                    parts.append(self._label(frame.f_code))
                    frame = frame.f_back
                    depth += 1
                if not parts:
                    continue
                key = ";".join(reversed(parts))  # root → leaf
                if key not in self._counts and len(self._counts) >= MAX_STACKS:
                    key = OVERFLOW_KEY
                self._counts[key] = self._counts.get(key, 0) + 1
                folded += 1
            self._samples += 1
        dt = time.perf_counter() - t0
        self._busy_s += dt
        self._m_samples.inc()
        self._m_pass.observe(dt)
        return folded

    def _loop(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                self.sample_once()
            except Exception:
                # sampling must never take the process down; skip the beat
                continue

    # -- views -------------------------------------------------------------

    @property
    def samples(self) -> int:
        return self._samples

    def overhead_ratio(self) -> float:
        """Busy seconds inside sampling passes / wall seconds since
        start — the measured overhead the <1% gate asserts on."""
        if self._started_mono is None:
            return 0.0
        wall = time.monotonic() - self._started_mono
        return (self._busy_s / wall) if wall > 0 else 0.0

    def folded(self, top: int | None = None) -> str:
        """Folded-stack text (``stack count`` per line, hottest first) —
        the flamegraph input format, and what ``/profile`` serves."""
        with self._lock:
            items = sorted(
                self._counts.items(), key=lambda kv: (-kv[1], kv[0])
            )
        if top is not None:
            items = items[:top]
        return "\n".join(f"{k} {v}" for k, v in items)

    def profile_text(self) -> str:
        """``/profile`` response body: a comment header (hz, samples,
        measured overhead — every parser skips ``#`` lines) + folded
        stacks."""
        head = (
            f"# astpu-profile hz={self.hz:g} samples={self._samples} "
            f"overhead={self.overhead_ratio():.5f} "
            f"started={self._started_ts or 0:.3f} pid={os.getpid()}"
        )
        body = self.folded()
        return head + ("\n" + body if body else "") + "\n"

    def clear(self) -> None:
        with self._lock:
            self._counts.clear()
            self._samples = 0
        self._busy_s = 0.0
        if self._started_mono is not None:
            self._started_mono = time.monotonic()


# -- process-global sampler ---------------------------------------------------

_global_lock = threading.Lock()
_GLOBAL: StackSampler | None = None

_TRUTHY = ("1", "true", "yes", "on")


def resolve_profile_hz() -> float:
    """``ASTPU_PROFILE`` → sampling hz: a number is the rate, a bare
    truthy flag means the default rate, anything else (or unset) is 0 =
    disabled."""
    v = os.environ.get("ASTPU_PROFILE", "").strip().lower()
    if not v:
        return 0.0
    if v in _TRUTHY:
        return DEFAULT_HZ
    try:
        hz = float(v)
    except ValueError:
        return 0.0
    return hz if hz > 0 else 0.0


def maybe_start_global() -> StackSampler | None:
    """Start the process-global sampler if ``ASTPU_PROFILE`` asks for one
    (idempotent).  Called by every exporter start (``StatusServer``), so
    any process that serves ``/metrics`` profiles itself under the env
    knob with no extra wiring."""
    hz = resolve_profile_hz()
    if hz <= 0:
        return None
    return ensure_global(hz)


def ensure_global(hz: float = DEFAULT_HZ) -> StackSampler:
    """Start (or return) the process-global sampler at ``hz``."""
    global _GLOBAL
    with _global_lock:
        if _GLOBAL is None or not _GLOBAL.running:
            _GLOBAL = StackSampler(hz).start()
        return _GLOBAL


def global_sampler() -> StackSampler | None:
    return _GLOBAL


def stop_global() -> None:
    global _GLOBAL
    with _global_lock:
        if _GLOBAL is not None:
            _GLOBAL.stop()
            _GLOBAL = None


def profile_response_text() -> str:
    """The ``GET /profile`` body for this process: the global sampler's
    folded view, or a one-line comment naming the knob when profiling is
    off (a 200 either way — a scraping collector must tell "disabled"
    apart from "dead")."""
    s = _GLOBAL
    if s is None:
        return "# astpu-profile disabled (set ASTPU_PROFILE=<hz>)\n"
    return s.profile_text()


def serve_profile(handler) -> None:
    """Mount ``GET /profile`` on a ``BaseHTTPRequestHandler`` (shared by
    ``StatusServer`` and every sidecar that rides it)."""
    from advanced_scrapper_tpu.obs import telemetry

    telemetry.send_http_payload(
        handler,
        200,
        profile_response_text().encode("utf-8"),
        "text/plain; charset=utf-8",
    )
