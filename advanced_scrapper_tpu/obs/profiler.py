"""Profiling hooks: per-batch step timing + optional XLA trace export.

The reference's only performance observability is the 10 Hz stats line
(SURVEY.md §5.1); the TPU framework adds what that can't see — device step
latency percentiles and ``jax.profiler`` traces for the kernel timeline.
"""

from __future__ import annotations

import contextlib
import time
from collections import deque


class StepTimer:
    """Rolling per-step duration tracker (device batches, host stages).

    Production step loops own one (``pipeline.feed.DeviceFeed.timer``,
    ``pipeline.dedup.NearDupEngine.step_timer``) so :meth:`summary` is
    reachable live, and each observation can mirror into a telemetry
    histogram (``histogram=``) so the same steps show on ``/metrics`` —
    the registry hands back a no-op handle when telemetry is disabled,
    keeping the mirrored path free.  Appends are deque ops (thread-safe
    under the GIL); :meth:`summary` reads a snapshot.
    """

    def __init__(self, maxlen: int = 512, histogram=None):
        self._durations: deque[float] = deque(maxlen=maxlen)
        self._items: deque[int] = deque(maxlen=maxlen)
        self._histogram = histogram

    @contextlib.contextmanager
    def step(self, n_items: int = 1):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(time.perf_counter() - t0, n_items)

    def add(self, seconds: float, n_items: int = 1) -> None:
        """Record a step timed by the caller — for loops where the item
        count is only known after the work (e.g. a pop that may drain a
        partial tile)."""
        self._durations.append(seconds)
        self._items.append(n_items)
        if self._histogram is not None:
            self._histogram.observe(seconds)

    def summary(self) -> dict:
        if not self._durations:
            return {"steps": 0}
        ds = sorted(self._durations)
        total_t = sum(ds)
        total_n = sum(self._items)
        return {
            "steps": len(ds),
            "p50_ms": round(ds[len(ds) // 2] * 1e3, 3),
            "p95_ms": round(ds[int(len(ds) * 0.95)] * 1e3, 3),
            "items_per_sec": round(total_n / total_t, 1) if total_t > 0 else 0.0,
        }


@contextlib.contextmanager
def xla_trace(log_dir: str | None):
    """``jax.profiler.trace`` wrapper; no-op when ``log_dir`` is falsy."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
