"""Pod-scale packed dedup: the fused donated tile step over a device mesh.

The repo grew two device dedup paths that had never met:

- the mesh-sharded combine (:func:`parallel.sharded.make_sharded_block_dedup`)
  — shard-local ``segment_min`` partials combined with ``lax.pmin`` — which
  still rode the OLD transport: three serialized puts and two unfused,
  undonated dispatches per tile;
- the single-dispatch plane (``ops.minhash.make_fused_tile_step`` +
  ``pipeline/dispatch.py``) — ONE packed ``device_put`` and ONE fused
  donated dispatch per tile, launch-count-asserted — which was
  single-device only.

This module is their unification: the PR 9 fused tile step *lifted into a
shard_map/pjit call over the mesh*.  The running accumulator is a global
``uint32[n_shards, num_articles, P]`` array sharded one row per device; a
tile group is a global ``uint8[n_shards, rows*(width+8)]`` packed buffer
assembled from per-shard ``jax.device_put``\\ s (one put per shard per
tile — each host puts only its local shards); the step unpacks, computes
block signatures, segment-mins per article, and folds into the DONATED
per-shard accumulator slice — all inside one partitioned dispatch, so each
device's per-tile traffic is exactly 1 put + 1 fused donated dispatch,
the same ledger contract the single-device plane certifies.  Donation
across the partitioned call is the hard part (SNIPPETS.md is pjit's
``donation_vector``/``rebase_donate_argnums`` internals — donation is
rebased per-shard under pjit, which is what makes the in-place
accumulator update survive partitioning); it is asserted per corpus via
``is_deleted()`` exactly like the single-device step.

The cross-shard combine happens ONCE, at end of corpus, in the resolve
epilogue: shard partials meet with ``lax.pmin`` over every mesh axis
(MinHash's min-algebra makes the blockwise + sharded combine exact —
identical math to ``make_sharded_block_dedup``, moved from per-dispatch
to per-corpus), then the standard LSH resolution runs replicated.  Band
keys for the persistent-index plane come off the same combined signatures
(:func:`make_sharded_keys_epilogue`); the *cross-shard band-key merge*
itself rides the index fleet (``index/fleet.py``) as the host-side plane.

Layering: this module is device math only — jax + ``core``/``ops``.  The
host pipeline around it (encode chunker, the pipelined executor, device
ledger) lives in ``pipeline/dedup.py``; ``parallel`` must never import
``pipeline``/``net``/``index``/``runtime`` (``tools/lint_imports.py``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from advanced_scrapper_tpu.core.hashing import MinHashParams
from advanced_scrapper_tpu.core.mesh import shard_map_compat
from advanced_scrapper_tpu.ops.lsh import (
    band_keys,
    band_keys_wide,
    duplicate_rep_bands,
    fine_edge_thresholds,
    resolve_rep_bands,
)
from advanced_scrapper_tpu.ops.minhash import resolve_signature_fn
from advanced_scrapper_tpu.ops.pack import unpack_tile
from advanced_scrapper_tpu.ops.shingle import U32_MAX

__all__ = [
    "assemble_packed_tiles",
    "local_shard_rows",
    "make_sharded_accumulator_init",
    "make_sharded_fused_tile_step",
    "make_sharded_keys_epilogue",
    "make_sharded_resolve_epilogue",
    "mesh_num_shards",
    "shard_row_devices",
]


def _shard_axes(mesh: Mesh) -> tuple:
    """Every mesh axis, as the dim-0 partition spec: a shard is a DEVICE
    (data × seq both count), so an 8-device mesh always runs 8 per-shard
    accumulators regardless of its (dp, sp) factorisation."""
    return tuple(mesh.axis_names)


def mesh_num_shards(mesh: Mesh) -> int:
    """Device count of the mesh = number of accumulator shards."""
    return int(np.prod([mesh.shape[a] for a in mesh.axis_names]))


def _row_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Dim-0-sharded (one row per device), trailing dims replicated."""
    return NamedSharding(mesh, P(_shard_axes(mesh), *([None] * (ndim - 1))))


def shard_row_devices(mesh: Mesh) -> list:
    """The device owning each row-shard of a dim-0-sharded global array,
    in row order — derived FROM the sharding's index map, never assumed
    from device-list order, so per-shard ``device_put``\\ s always land on
    the device that will own that row."""
    n = mesh_num_shards(mesh)
    sharding = _row_sharding(mesh, 1)
    order: list = [None] * n
    for dev, idx in sharding.devices_indices_map((n,)).items():
        # a 1-shard mesh reports the trivial slice(None) — row 0
        order[idx[0].start or 0] = dev
    return order


def local_shard_rows(mesh: Mesh) -> list[int]:
    """Row-shard indices owned by THIS process ("each host packs tiles
    for its local shard(s)") — all of them on a single-controller host."""
    pi = jax.process_index()
    return [
        i for i, d in enumerate(shard_row_devices(mesh))
        if d.process_index == pi
    ]


def assemble_packed_tiles(mesh: Mesh, shards: list, nbytes: int):
    """Bind per-shard ``uint8[1, nbytes]`` device buffers (already put on
    their row's device — ``shard_row_devices`` order) into ONE global
    ``uint8[n_shards, nbytes]`` sharded array.  Pure metadata: no copy,
    no transfer — the puts already happened, one per shard."""
    return jax.make_array_from_single_device_arrays(
        (mesh_num_shards(mesh), nbytes), _row_sharding(mesh, 2), shards
    )


def make_sharded_accumulator_init(mesh: Mesh, num_perm: int):
    """``init(num_articles=...)`` → the all-``U32_MAX`` (min-identity)
    running accumulator ``uint32[n_shards, num_articles, num_perm]``,
    filled ON DEVICE under the row sharding (no H2D transfer — exactly
    like the single-device path's ``jnp.full``, so the per-shard put
    ledger stays tiles + 1)."""
    nsh = mesh_num_shards(mesh)
    sharding = _row_sharding(mesh, 3)

    @partial(jax.jit, static_argnames=("num_articles",), out_shardings=sharding)
    def init(*, num_articles: int):
        return jnp.full((nsh, num_articles, num_perm), U32_MAX, jnp.uint32)

    return init


def make_sharded_fused_tile_step(mesh: Mesh, params: MinHashParams, backend: str):
    """The PR 9 fused tile step lifted into a shard_map over ``mesh``:
    ``(running, packed) -> running'`` with ``running`` DONATED.

    ``running`` is ``uint32[n_shards, num_articles, P]`` sharded one row
    per device, ``packed`` the ``uint8[n_shards, rows*(width+8)]`` tile
    group (:func:`assemble_packed_tiles`).  Each shard — inside the ONE
    partitioned dispatch — unpacks its own tile, computes block
    signatures, segment-mins them per article, and folds into its OWN
    accumulator row in place (pjit rebases the donation per shard, so no
    per-tile ``[num_articles, P]`` allocation on any device).  No
    collective runs here: shard partials stay local until the
    end-of-corpus epilogue's ``pmin``, keeping the per-tile critical path
    free of cross-device synchronisation.

    ``backend == "oph"`` uses the RAW OPH form (empty bins ``U32_MAX``)
    so the min-combine stays exact across blocks AND shards; the
    epilogues densify once after the ``pmin`` (``ops/oph.py`` on why
    that order is load-bearing).  Cache the returned callable per
    (engine, mesh) — jit then caches per static (rows, width,
    num_articles), the same shape set the single-device chunker draws
    (``pipeline.dedup``'s ``_tile_bs``/``_tile_rows_options``).

    SENTINEL CONTRACT: the raw ``jax.jit`` object is returned (exposing
    ``_cache_size``) so ``pipeline.dedup._get_sharded_fused_step`` can
    wrap it in the recompile sentinel (``obs.devprof.instrument_jit`` →
    ``astpu_jit_compiles_total{kernel="sharded_fused_tile"}``; parallel
    may not grow an obs dependency for it — layering keeps the counting
    at the pipeline seam).
    """
    if backend == "oph":
        from advanced_scrapper_tpu.ops.oph import oph_raw_signatures

        block_fn = oph_raw_signatures
    else:
        block_fn = resolve_signature_fn(backend)
    axes = _shard_axes(mesh)
    spec_run = P(axes, None, None)
    spec_packed = P(axes, None)

    @partial(
        jax.jit,
        static_argnames=("rows", "width", "num_articles"),
        donate_argnums=(0,),
    )
    def sharded_tile_step(
        running: jnp.ndarray,
        packed: jnp.ndarray,
        *,
        rows: int,
        width: int,
        num_articles: int,
    ) -> jnp.ndarray:
        def local(run_l, packed_l):
            # run_l: uint32[1, num_articles, P]; packed_l: uint8[1, nbytes]
            tok, lens, owners = unpack_tile(packed_l[0], rows, width)
            sigs = block_fn(tok, lens, params)
            part = jax.ops.segment_min(
                sigs, owners, num_segments=num_articles,
                indices_are_sorted=False,
            )
            return jnp.minimum(run_l, part[None])

        return shard_map_compat(
            local,
            mesh=mesh,
            in_specs=(spec_run, spec_packed),
            out_specs=spec_run,
        )(running, packed)

    return sharded_tile_step


def make_sharded_resolve_epilogue(
    mesh: Mesh,
    params: MinHashParams,
    *,
    threshold: float,
    fine_margin: float,
    fine_salt: np.ndarray,
    backend: str,
):
    """``epilogue(running, valid, jump_rounds=...) -> rep`` — the ONE
    end-of-corpus dispatch of the sharded packed plane.

    This is where the cross-shard combine lives: shard partials meet with
    ``lax.pmin`` over every mesh axis (exactly the
    ``make_sharded_block_dedup`` combine, hoisted from per-dispatch to
    per-corpus), the OPH densify runs once AFTER it, and the standard
    estimator-only LSH resolution (coarse+fine candidate keys → per-band
    candidates → optional per-edge fine bars → verified union-find)
    follows, replicated on every shard — identical math to
    ``ops.lsh.fused_resolve_epilogue``, so the replicated ``int32[N]``
    output is byte-identical to the single-device fused oracle.  ``valid``
    is the replicated host eligibility mask (the async path's
    ``_valid_device`` put, one per shard)."""
    use_oph = backend == "oph"
    axes = _shard_axes(mesh)
    salt = jnp.asarray(params.band_salt)
    fine = jnp.asarray(fine_salt)
    use_fine_margin = bool(fine_salt.shape[0] and fine_margin)

    @partial(jax.jit, static_argnames=("jump_rounds",))
    def sharded_resolve(running, valid, *, jump_rounds: int):
        def local(run_l, valid_l):
            sig = jax.lax.pmin(run_l[0], axes)
            if use_oph:
                from advanced_scrapper_tpu.ops.oph import densify

                sig = densify(sig)
            keys = band_keys(sig, salt)
            if fine.shape[0]:
                keys = jnp.concatenate([keys, band_keys(sig, fine)], axis=1)
            rep_bands = duplicate_rep_bands(keys, valid_l)
            if use_fine_margin:
                thr = fine_edge_thresholds(
                    rep_bands, keys, threshold, fine_margin,
                    num_coarse=params.num_bands,
                )
            else:
                thr = jnp.float32(threshold)
            return resolve_rep_bands(
                rep_bands, sig, valid_l, thr, jump_rounds=jump_rounds
            )

        return shard_map_compat(
            local,
            mesh=mesh,
            in_specs=(P(axes, None, None), P(None)),
            out_specs=P(None),
        )(running, valid)

    return sharded_resolve


def make_sharded_keys_epilogue(mesh: Mesh, params: MinHashParams, backend: str):
    """``keys_epilogue(running) -> uint32[N, nb, 2]`` — the wide two-lane
    band keys (``ops.lsh.band_keys_wide``) off the pmin-combined sharded
    accumulator, one dispatch, replicated.  Feeds the persistent-index
    plane: the HOST then packs them 64-bit and fans them out per *index*
    shard through ``index.fleet.ShardedIndexClient`` — the cross-shard
    band-key merge is the fleet's consistent-hash ring, not a device
    collective, so a device-mesh shard count and an index-fleet shard
    count never have to agree."""
    use_oph = backend == "oph"
    axes = _shard_axes(mesh)
    salt = jnp.asarray(params.band_salt)

    @jax.jit
    def sharded_keys(running):
        def local(run_l):
            sig = jax.lax.pmin(run_l[0], axes)
            if use_oph:
                from advanced_scrapper_tpu.ops.oph import densify

                sig = densify(sig)
            return band_keys_wide(sig, salt)

        return shard_map_compat(
            local,
            mesh=mesh,
            in_specs=(P(axes, None, None),),
            out_specs=P(None),
        )(running)

    return sharded_keys
