"""Ring-pass cross-shard dedup — memory-scalable resolution over ICI.

The all-gather path (``parallel/sharded.py``) replicates every shard's band
keys + signatures on every device: fine at batch sizes where 576 B/article
× B fits HBM, but the footprint grows with the *global* batch.  This module
is the ring formulation (the ring-attention pattern applied to dedup): each
device keeps only its local block and a same-sized transit block that
rotates around the mesh's data axis via ``lax.ppermute``; after
``n_shards`` hops every pair of blocks has met.  Peak per-device payload is
O(local batch) regardless of global batch — only the final 4-byte/row
representative array is ever globally resolved.

Matching at each hop is sort + searchsorted (the XLA-idiomatic hash join):
for every band, the transit block's (key, global-index) pairs are sorted so
the run head at the searchsorted position is the *earliest* global row with
that band key; signature agreement is verified at meet time, so a
candidate is only accepted when it is a true near-duplicate
(``agreement >= threshold``) with a smaller global index.  Sort order is a
property of the block and invariant under rotation, so each block is sorted
*once* (one batched multi-operand ``lax.sort`` over all bands) before
entering the ring and the sorted arrays rotate — hops do only searchsorted
joins, no sorting.

Semantics match the all-gather path on well-separated corpora (documents
either near-identical or dissimilar); on borderline-similarity chains the
two paths may pick different-but-valid representatives, since this path
verifies every met candidate while the gather path verifies only the
band-proposed one.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from advanced_scrapper_tpu.core.hashing import MinHashParams
from advanced_scrapper_tpu.ops.lsh import band_keys
from advanced_scrapper_tpu.ops.minhash import minhash_signatures
from advanced_scrapper_tpu.ops.shingle import U32_MAX


def _presort_bands(keys: jnp.ndarray, gidx_eff: jnp.ndarray):
    """Per-band sort of a block's (key, global-index, row) triples.

    ``keys`` is ``uint32[Bt, nb]`` (invalid rows already ``U32_MAX``),
    ``gidx_eff`` is ``int32[Bt]`` with invalid rows set to int32-max so they
    sort last and can never head a run.  One batched multi-operand sort over
    the band axis; returns ``(sk, sg, sp)`` each ``[nb, Bt]`` where the run
    head at a searchsorted position is the *earliest* global row with that
    band key.
    """
    Bt, nb = keys.shape
    rowpos = jnp.broadcast_to(jnp.arange(Bt, dtype=jnp.int32), (nb, Bt))
    g = jnp.broadcast_to(gidx_eff, (nb, Bt))
    return jax.lax.sort((keys.T, g, rowpos), dimension=1, num_keys=2)


def _best_match_against_sorted(
    keys_l: jnp.ndarray,   # uint32[Bl, nb]  local band keys (invalid → U32_MAX)
    sig_l: jnp.ndarray,    # uint32[Bl, P]
    gidx_l: jnp.ndarray,   # int32[Bl]   local global row indices
    sk: jnp.ndarray,       # uint32[nb, Bt]  transit keys, per-band sorted
    sp: jnp.ndarray,       # int32[nb, Bt]   block row in sort order
    gidx_eff: jnp.ndarray,  # int32[Bt]  transit global idx, block order (invalid → max)
    sig_b: jnp.ndarray,    # uint32[Bt, P]   transit signatures (block order)
    threshold: float,
) -> jnp.ndarray:
    """int32[Bl]: smallest transit global index that band-collides with the
    local row AND verifies by signature agreement; own index otherwise.

    Bands reduce inside a ``lax.scan`` so the per-hop transient stays at
    O(Bl·P) — one band's candidate-signature gather at a time — instead of
    materialising the [nb, Bl, P] gather all at once (which would be ~16×
    the ring payload this module exists to avoid).  The per-band global
    indices are recovered as ``gidx_eff[sp]`` rather than rotated as their
    own [nb, Bt] matrix, keeping the ring payload minimal.
    """
    Bt = sk.shape[1]
    big = jnp.iinfo(jnp.int32).max

    def band_body(best, xs):
        skb, spb, klb = xs  # uint32[Bt], int32[Bt], uint32[Bl]
        pos = jnp.clip(jnp.searchsorted(skb, klb, side="left"), 0, Bt - 1)
        hit = skb[pos] == klb
        row = spb[pos]
        cand_gidx = gidx_eff[row]
        cand_sig = sig_b[row]                             # [Bl, P]
        agree = (sig_l == cand_sig).mean(axis=1)
        ok = hit & (agree >= threshold) & (cand_gidx < gidx_l)
        return jnp.minimum(best, jnp.where(ok, cand_gidx, big)), None

    init = jnp.full_like(gidx_l, big)
    best, _ = jax.lax.scan(band_body, init, (sk, sp, keys_l.T))
    return jnp.where(best == big, gidx_l, best)


def make_ring_dedup(
    mesh: Mesh,
    params: MinHashParams,
    *,
    threshold: float = 0.7,
    jump_rounds: int = 20,
):
    """Build the jitted ring-resolution dedup step for ``mesh``.

    Returns ``step(tokens, lengths) -> rep`` with ``tokens`` sharded on the
    data axis and ``rep`` the replicated ``int32[B]`` global first-seen
    representative array (union-find roots after pointer jumping).
    """
    data = mesh.axis_names[0]
    n = mesh.shape[data]
    salt = jnp.asarray(params.band_salt)
    k = params.shingle_k

    def local_step(tokens, lengths):
        # tokens: uint8[Bl, L] local shard
        Bl = tokens.shape[0]
        sig = minhash_signatures(tokens, lengths, params)
        keys = band_keys(sig, salt)
        valid = lengths >= k
        keys = jnp.where(valid[:, None], keys, U32_MAX)
        shard = jax.lax.axis_index(data)
        gidx = (shard * Bl + jnp.arange(Bl)).astype(jnp.int32)

        perm = [(s, (s + 1) % n) for s in range(n)]

        # Sort once before entering the ring; what rotates is the sorted
        # (key, row) pairs plus the block-order gidx vector and signatures
        # that sp indexes into — the sorted global indices are derivable as
        # gidx_eff[sp], so they are never carried as their own matrix.
        big = jnp.iinfo(jnp.int32).max
        gidx_eff = jnp.where(valid, gidx, big)
        sk, _sg, sp = _presort_bands(keys, gidx_eff)

        def hop(_, carry):
            rep, blk = carry
            cand = _best_match_against_sorted(keys, sig, gidx, *blk, threshold)
            rep = jnp.minimum(rep, cand)
            blk = tuple(jax.lax.ppermute(x, data, perm) for x in blk)
            return rep, blk

        init = (gidx, (sk, sp, gidx_eff, sig))
        rep, _ = jax.lax.fori_loop(0, n, hop, init)

        # Chain resolution on the 4-byte/row rep array only — the heavy
        # payloads (keys 64 B, sigs 512 B per row) never left the ring.
        g_rep = jax.lax.all_gather(rep, data, axis=0, tiled=True)
        for _ in range(jump_rounds):
            g_rep = jnp.take(g_rep, g_rep)
        return g_rep

    from advanced_scrapper_tpu.core.mesh import shard_map_compat

    sharded = shard_map_compat(
        local_step,
        mesh=mesh,
        in_specs=(P(data, None), P(data)),
        out_specs=P(None),
    )
    return jax.jit(sharded)
