"""Mesh-sharded dedup — the framework's distributed compute path.

The reference's only multi-node backend is a TCP master/worker star with
20-URL leases (``server1.py:102-138``, SURVEY.md §5.8).  Here distribution is
SPMD over a ``jax.sharding.Mesh`` with XLA collectives on ICI:

- **data axis (dp)** — the batch is sharded; each shard computes local
  MinHash signatures and band keys, then ``all_gather``\\ s the (small) band
  keys so every shard resolves first-seen-wins representatives against the
  *global* corpus.  Band keys are 16 uint32 per article — gathering them is
  64 bytes/article on ICI, three orders of magnitude less than gathering
  articles.
- **seq axis (sp)** — long articles are sharded along the byte axis; each
  shard hashes its slice (after a (k-1)-byte **halo exchange** with
  ``lax.ppermute`` so no shingle is lost at shard boundaries) and partial
  signatures combine with ``lax.pmin`` — MinHash's min-algebra makes
  sequence parallelism exact.
- the LSH bucket-count histogram merges across shards with ``lax.psum``
  (the collective the north star names for bucket merge).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from advanced_scrapper_tpu.core.hashing import MinHashParams
from advanced_scrapper_tpu.core.mesh import shard_map_compat
from advanced_scrapper_tpu.ops.lsh import (
    band_keys,
    bucket_histogram,
    candidate_keys,
    duplicate_rep_bands,
    fine_edge_thresholds,
    resolve_rep_bands,
)
from advanced_scrapper_tpu.ops.minhash import (
    minhash_signatures,
    resolve_signature_fn,
    scan_min_signature,
)
from advanced_scrapper_tpu.ops.shingle import shingle_hash


def _data_axis(mesh: Mesh) -> str:
    return mesh.axis_names[0]


def _seq_axis(mesh: Mesh) -> str:
    return mesh.axis_names[1] if len(mesh.axis_names) > 1 else None


def make_sharded_dedup(
    mesh: Mesh,
    params: MinHashParams,
    *,
    threshold: float = 0.7,
    jump_rounds: int = 16,
    hist_bins: int = 1 << 16,
    backend: str = "scan",
    cand_subbands: int | None = None,
    fine_margin: float | None = None,
):
    """Build the jitted batch-sharded dedup step for ``mesh``.

    Returns ``step(tokens, lengths) -> (rep, hist)`` where ``tokens`` is
    ``uint8[B, L]`` sharded on the data axis, ``rep`` is the replicated
    ``int32[B]`` global first-seen representative array, and ``hist`` the
    psum-merged bucket histogram.  ``backend="oph"`` swaps the dense
    signature kernel for one-permutation hashing (``ops/oph.py``) — data
    shards own whole rows, so densification is safe shard-local.

    Resolution is the same verified-candidate connected-components as the
    batch engine (``duplicate_rep_bands`` + ``resolve_rep_bands``, with
    ``cand_subbands`` fine candidate bands): the streamed path must not
    recall less than the certified one-shot path.
    """
    data = _data_axis(mesh)
    salt = jnp.asarray(params.band_salt)
    k = params.shingle_k
    _sig_fn = resolve_signature_fn(backend)
    if cand_subbands is None or fine_margin is None:
        # single source of the defaults: the certified engine's config
        from advanced_scrapper_tpu.config import DedupConfig

        if cand_subbands is None:
            cand_subbands = DedupConfig().cand_subbands
        if fine_margin is None:
            fine_margin = DedupConfig().fine_margin

    def local_step(tokens, lengths):
        # tokens: uint8[B/n, L] local shard
        sig = _sig_fn(tokens, lengths, params)
        keys = band_keys(sig, salt)
        valid = lengths >= k
        all_keys = candidate_keys(sig, salt, cand_subbands)
        # Cross-shard candidate resolution: gather the compact per-article
        # summaries (keys: 64-192 B, sig: 512 B per article) — never the text.
        g_keys = jax.lax.all_gather(all_keys, data, axis=0, tiled=True)
        g_sig = jax.lax.all_gather(sig, data, axis=0, tiled=True)
        g_valid = jax.lax.all_gather(valid, data, axis=0, tiled=True)
        rep_bands = duplicate_rep_bands(g_keys, g_valid)
        if cand_subbands and fine_margin:
            thr = fine_edge_thresholds(
                rep_bands, g_keys, threshold, fine_margin,
                num_coarse=params.num_bands,
            )
        else:
            thr = jnp.float32(threshold)
        rep = resolve_rep_bands(
            rep_bands, g_sig, g_valid, thr, jump_rounds=jump_rounds
        )
        # North-star bucket merge: psum of per-shard histograms over ICI.
        hist = bucket_histogram(keys, valid, nbins=hist_bins)
        hist = jax.lax.psum(hist, data)
        return rep, hist

    # Keep the minhash scan inside shard_map so XLA never sees the global
    # batch; outputs are replicated.
    spec_in = (P(data, None), P(data))
    spec_out = (P(None), P(None))
    sharded = shard_map_compat(
        local_step, mesh=mesh, in_specs=spec_in, out_specs=spec_out
    )
    return jax.jit(sharded)


def make_sharded_block_dedup(
    mesh: Mesh,
    params: MinHashParams,
    num_articles: int,
    *,
    threshold: float = 0.7,
    jump_rounds: int = 16,
    hist_bins: int = 1 << 16,
    backend: str = "scan",
    cand_subbands: int | None = None,
    fine_margin: float | None = None,
):
    """Blockwise sharded dedup with the per-article segment-min combine
    FUSED into the device step.

    ``step(tokens, lengths, owners) -> (rep, hist)``: ``tokens`` is
    ``uint8[B, L]`` of BLOCKS (long articles split blockwise with k-1
    overlap, exactly like ``core.tokenizer.encode_blocks``) sharded on the
    data axis, ``owners int32[B]`` maps each block to its global article id
    (padding rows point at ``num_articles``, a scratch slot).  Each shard
    folds its local blocks into a per-article partial signature with
    ``segment_min`` and the partials combine across shards with
    ``lax.pmin`` — MinHash's min-algebra makes the blockwise+sharded
    combine exact, and fusing it here removes the host-side combine pass
    (sig D2H → numpy segment-min → re-H2D for resolution) that used to sit
    between the streaming feed and LSH resolution.  Only the compact
    ``[num_articles, P]`` partials ride the ICI, never block signatures.

    Resolution from the combined per-article signatures is identical to
    :func:`make_sharded_dedup` (same candidate bands, same fine thresholds),
    so streamed blockwise corpora resolve exactly like the row-per-article
    step — parity-tested against ``NearDupEngine`` in
    ``tests/test_encode_parity.py``.
    """
    data = _data_axis(mesh)
    salt = jnp.asarray(params.band_salt)
    k = params.shingle_k
    _sig_fn = resolve_signature_fn(backend)
    use_oph = backend == "oph"
    if use_oph:
        # raw OPH form through the combine; densify AFTER (ops/oph.py on
        # why that order is load-bearing for blockwise exactness)
        from advanced_scrapper_tpu.ops.oph import densify, oph_raw_signatures

        _sig_fn = oph_raw_signatures
    if cand_subbands is None or fine_margin is None:
        from advanced_scrapper_tpu.config import DedupConfig

        if cand_subbands is None:
            cand_subbands = DedupConfig().cand_subbands
        if fine_margin is None:
            fine_margin = DedupConfig().fine_margin
    n_seg = num_articles + 1  # +1 scratch row for padding blocks

    def local_step(tokens, lengths, owners):
        # tokens: uint8[B/n, L] local block shard; owners: int32[B/n] global
        block_sig = _sig_fn(tokens, lengths, params)
        # fused combine: local segment-min, then min across shards — blocks
        # of one article may land on different shards and still fold exactly
        part = jax.ops.segment_min(block_sig, owners, num_segments=n_seg)
        sig = jax.lax.pmin(part, data)[:num_articles]
        if use_oph:
            sig = densify(sig)
        blk_valid = (lengths >= k).astype(jnp.int32)
        v_part = jax.ops.segment_max(blk_valid, owners, num_segments=n_seg)
        valid = jax.lax.pmax(v_part, data)[:num_articles] > 0
        keys = band_keys(sig, salt)
        all_keys = candidate_keys(sig, salt, cand_subbands)
        rep_bands = duplicate_rep_bands(all_keys, valid)
        if cand_subbands and fine_margin:
            thr = fine_edge_thresholds(
                rep_bands, all_keys, threshold, fine_margin,
                num_coarse=params.num_bands,
            )
        else:
            thr = jnp.float32(threshold)
        rep = resolve_rep_bands(
            rep_bands, sig, valid, thr, jump_rounds=jump_rounds
        )
        hist = bucket_histogram(keys, valid, nbins=hist_bins)
        return rep, hist

    sharded = shard_map_compat(
        local_step,
        mesh=mesh,
        in_specs=(P(data, None), P(data), P(data)),
        out_specs=(P(None), P(None)),
    )
    return jax.jit(sharded)


def make_seq_sharded_signatures(
    mesh: Mesh,
    params: MinHashParams,
    block_len: int,
    *,
    chunk: int = 512,
):
    """Build the jitted sequence-parallel MinHash kernel for ``mesh``.

    Byte axis sharded over the mesh's seq axis, exactly equivalent to the
    single-device kernel: each shard hashes its byte slice extended by a
    (k-1)-byte halo fetched from the next shard via ``lax.ppermute``, masks
    shingle validity against *global* positions, scans permutation minima in
    ``chunk``-sized pieces (peak intermediate ``[B, chunk, 128]`` per shard),
    and combines partials with ``lax.pmin`` over the seq axis.  The
    wrap-around halo on the last shard is always masked out (global positions
    past the text end are invalid by construction).
    """
    data = _data_axis(mesh)
    seq = _seq_axis(mesh)
    if seq is None:
        raise ValueError("mesh has no seq axis")
    n_seq = mesh.shape[seq]
    a32 = jnp.asarray(params.a32)
    b32 = jnp.asarray(params.b32)
    k = params.shingle_k
    if block_len % n_seq:
        raise ValueError(f"block length {block_len} not divisible by seq={n_seq}")
    Ls = block_len // n_seq

    def kernel(tok_l, len_l):
        # tok_l: uint8[Bl, Ls]; len_l: int32[Bl] (full lengths, replicated on seq)
        idx = jax.lax.axis_index(seq)
        # halo: first k-1 bytes of the *next* shard (wraps; masked below)
        perm = [(i, (i - 1) % n_seq) for i in range(n_seq)]
        halo = jax.lax.ppermute(tok_l[:, : k - 1], seq, perm)
        ext = jnp.concatenate([tok_l, halo], axis=1)  # [Bl, Ls + k - 1]
        start = idx * Ls
        # valid shingle at local pos i  ⇔  global pos start+i ≤ len-k
        eff = jnp.clip(len_l - start, 0, Ls + k - 1).astype(jnp.int32)
        h, valid = shingle_hash(ext, eff, k)  # [Bl, Ls]
        partial_sig = scan_min_signature(h, valid, a32, b32, chunk)
        return jax.lax.pmin(partial_sig, seq)

    sharded = shard_map_compat(
        kernel,
        mesh=mesh,
        in_specs=(P(data, seq), P(data)),
        out_specs=P(data, None),
    )
    return jax.jit(sharded)


# jit-cache for the convenience wrapper: keyed on mesh (hashable) and params
# identity (held strongly here, so the id cannot be recycled while cached).
_SEQ_KERNEL_CACHE: dict = {}


def seq_sharded_signatures(tokens, lengths, params: MinHashParams, mesh: Mesh, *, chunk: int = 512):
    """Convenience wrapper around :func:`make_seq_sharded_signatures`; reuses
    compiled kernels across calls with the same (mesh, params, shape)."""
    L = tokens.shape[-1]
    key = (mesh, id(params), L, chunk)
    entry = _SEQ_KERNEL_CACHE.get(key)
    if entry is None:
        entry = (make_seq_sharded_signatures(mesh, params, L, chunk=chunk), params)
        _SEQ_KERNEL_CACHE[key] = entry
    return entry[0](tokens, lengths)


def sharded_dedup_step(tokens, lengths, params: MinHashParams, mesh: Mesh, **kw):
    """One-shot convenience wrapper around :func:`make_sharded_dedup`."""
    step = make_sharded_dedup(mesh, params, **kw)
    return step(tokens, lengths)


def shard_batch(tokens, lengths, mesh: Mesh):
    """Place host arrays on the mesh with batch sharded over the data axis."""
    data = _data_axis(mesh)
    t = jax.device_put(tokens, NamedSharding(mesh, P(data, None)))
    l = jax.device_put(lengths, NamedSharding(mesh, P(data)))
    return t, l
