from advanced_scrapper_tpu.parallel.sharded import (
    sharded_dedup_step,
    seq_sharded_signatures,
    make_seq_sharded_signatures,
    make_sharded_dedup,
    shard_batch,
)
from advanced_scrapper_tpu.parallel.sharded_packed import (
    make_sharded_fused_tile_step,
    make_sharded_keys_epilogue,
    make_sharded_resolve_epilogue,
)
from advanced_scrapper_tpu.parallel.dist import initialize_multihost

__all__ = [
    "sharded_dedup_step",
    "seq_sharded_signatures",
    "make_seq_sharded_signatures",
    "make_sharded_dedup",
    "make_sharded_fused_tile_step",
    "make_sharded_keys_epilogue",
    "make_sharded_resolve_epilogue",
    "shard_batch",
    "initialize_multihost",
]
