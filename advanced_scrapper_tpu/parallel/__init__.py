from advanced_scrapper_tpu.parallel.sharded import (
    sharded_dedup_step,
    seq_sharded_signatures,
    make_seq_sharded_signatures,
    make_sharded_dedup,
    shard_batch,
)
from advanced_scrapper_tpu.parallel.dist import initialize_multihost

__all__ = [
    "sharded_dedup_step",
    "seq_sharded_signatures",
    "make_seq_sharded_signatures",
    "make_sharded_dedup",
    "shard_batch",
    "initialize_multihost",
]
