"""Multi-host initialisation.

The reference reaches multiple machines with a hand-rolled TCP protocol and
manual CSV splits (``server1.py``, ``experiental/split.py``).  The TPU-native
equivalent is ``jax.distributed``: one process per host, XLA collectives over
ICI within a slice and DCN across slices.  The host-side work distribution
(URL leases, requeue-on-disconnect — planned in ``net/``) is separate; this
module only brings up the device world.
"""

from __future__ import annotations

import os

import jax


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialise ``jax.distributed`` when a multi-host world is configured.

    Returns True if distributed mode was initialised.  Controlled by
    arguments or the standard JAX env vars; a no-op single-host fallback
    keeps every pipeline runnable on one machine (the reference's scripts
    likewise default to localhost, ``server1.py:17-18``).
    """
    addr = coordinator_address or os.environ.get("ASTPU_COORDINATOR")
    if addr is None:
        return False
    if num_processes is None:
        num_processes = int(os.environ.get("ASTPU_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("ASTPU_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def world_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
