"""Multi-host world: initialisation + the global-mesh dedup path.

The reference reaches multiple machines with a hand-rolled TCP protocol and
manual CSV splits (``server1.py``, ``experiental/split.py``).  The TPU-native
equivalent is ``jax.distributed``: one process per host, XLA collectives over
ICI within a slice and DCN across slices.  The host-side work distribution
(URL leases, requeue-on-disconnect — ``net/lease.py``) is separate; this
module brings up the device world and runs the sharded dedup step over the
*global* mesh: every host contributes its local batch shard, cross-host
candidate resolution rides the same ``all_gather``/``psum`` collectives as
the single-host path (``parallel/sharded.py``), and the replicated outputs
are addressable on every host.  Exercised for real by
``tests/test_multihost.py``: 2- and 4-process ``jax.distributed`` worlds on one box
(the reference tests its distributed stack the same way — server and client
both default to localhost, ``server1.py:17-18``).
"""

from __future__ import annotations

import os

import jax
import numpy as np


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialise ``jax.distributed`` when a multi-host world is configured.

    Returns True if distributed mode was initialised.  Controlled by
    arguments or the standard JAX env vars; a no-op single-host fallback
    keeps every pipeline runnable on one machine (the reference's scripts
    likewise default to localhost, ``server1.py:17-18``).
    """
    addr = coordinator_address or os.environ.get("ASTPU_COORDINATOR")
    if addr is None:
        return False
    if num_processes is None:
        num_processes = int(os.environ.get("ASTPU_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("ASTPU_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def world_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def global_mesh(n_seq: int = 1):
    """Mesh over every device in the world (all hosts), data × seq.

    ``jax.devices()`` lists process 0's devices first, so the data axis is
    process-major: host *p*'s local batch occupies global rows
    ``[p*B_local, (p+1)*B_local)`` — the index space representative ids
    refer to.
    """
    from advanced_scrapper_tpu.core.mesh import build_mesh

    return build_mesh(-1, n_seq)


def distribute_global_batch(tokens, lengths, mesh):
    """Per-host local batch → global arrays sharded over the data axis.

    Each process passes only its own ``uint8[B_local, L]`` shard; the global
    batch (``B_local × process_count`` rows, process-major) is assembled
    without any host ever holding it — the multi-host successor of
    ``shard_batch`` (and of the reference's manual ``split.py`` sharding).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    data = mesh.axis_names[0]
    t = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(data, None)), np.asarray(tokens)
    )
    l = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(data)), np.asarray(lengths)
    )
    return t, l


# step-function cache: keyed on mesh (hashable) + the params VALUE (its
# arrays are pure functions of these four ints, see core.hashing.make_params)
# + kwargs.  Bounded: long-lived processes creating many param variants must
# not pin compiled executables forever; eviction is insertion-order (dicts
# preserve it) — effectively FIFO, fine for a compile cache this small.
_DEDUP_STEP_CACHE: dict = {}
_DEDUP_STEP_CACHE_MAX = 16


def multihost_dedup(local_tokens, local_lengths, params, mesh=None, **kw):
    """Global first-seen dedup across all hosts' local batches.

    Runs ``parallel.sharded.make_sharded_dedup`` over the global mesh:
    signatures/band keys are computed shard-local, candidate resolution
    ``all_gather``\\ s the compact summaries across hosts (DCN), and the
    bucket histogram merges with ``psum``.  Returns host-local numpy
    ``(rep, hist)`` — identical on every host (replicated outputs).
    ``rep[i]`` indexes the process-major global batch (see
    :func:`global_mesh`).
    """
    from advanced_scrapper_tpu.parallel.sharded import make_sharded_dedup

    if mesh is None:
        mesh = global_mesh()
    t, l = distribute_global_batch(local_tokens, local_lengths, mesh)
    key = (
        mesh,
        params.num_perm, params.num_bands, params.shingle_k, params.seed,
        tuple(sorted(kw.items())),
    )
    step = _DEDUP_STEP_CACHE.pop(key, None)
    if step is None:
        while len(_DEDUP_STEP_CACHE) >= _DEDUP_STEP_CACHE_MAX:
            _DEDUP_STEP_CACHE.pop(next(iter(_DEDUP_STEP_CACHE)))
        step = make_sharded_dedup(mesh, params, **kw)
    _DEDUP_STEP_CACHE[key] = step  # (re-)insert at the back: LRU eviction
    rep, hist = step(t, l)
    return (
        np.asarray(jax.device_get(rep.addressable_data(0))),
        np.asarray(jax.device_get(hist.addressable_data(0))),
    )
