"""Typed configuration for every subsystem.

The reference has no config system at all — every knob is a module-level
constant (``constant_rate_scrapper.py:17-28``, ``client1.py:17-24``,
``03_worker_multi.py:31``; SURVEY.md §5.6).  Here each subsystem gets a
frozen dataclass whose *defaults are the reference's operating points*, with
overrides from environment variables (``ASTPU_<FIELD>``) and from the CLI.
"""

from __future__ import annotations

import dataclasses
import os
import typing
from dataclasses import dataclass, field, fields
from typing import Any, Type, TypeVar

T = TypeVar("T")

_ENV_PREFIX = "ASTPU_"


def _coerce(raw: str, typ: Any) -> Any:
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(raw)
    if typ is float:
        return float(raw)
    if typ is tuple or typing.get_origin(typ) is tuple:
        return tuple(float(part) for part in raw.split(",") if part.strip())
    return raw


def from_env(cls: Type[T], section: str = "", **overrides: Any) -> T:
    """Build a config dataclass from ``ASTPU_<SECTION>_<FIELD>`` env vars.

    The section prefix keeps same-named fields in different subsystems
    independent (``ASTPU_DEDUP_BATCH_SIZE`` vs ``ASTPU_FEED_BATCH_SIZE``).
    """
    kwargs: dict[str, Any] = {}
    # PEP 563 postponed annotations make ``field.type`` a string; resolve the
    # real types so _coerce's identity checks work.
    hints = typing.get_type_hints(cls)
    prefix = _ENV_PREFIX + (section.upper() + "_" if section else "")
    for f in fields(cls):  # type: ignore[arg-type]
        env_key = prefix + f.name.upper()
        if env_key in os.environ:
            kwargs[f.name] = _coerce(os.environ[env_key], hints.get(f.name, str))
    kwargs.update({k: v for k, v in overrides.items() if v is not None})
    return cls(**kwargs)  # type: ignore[call-arg]


@dataclass(frozen=True)
class ScraperConfig:
    """Constant-rate acquisition engine (ref constant_rate_scrapper.py:17-28)."""

    website: str = "yfin"
    input_csv: str = "yfin_urls.csv"
    desired_request_rate: float = 5.8   # ref :17
    max_threads: int = 16               # ref :20
    stats_time_window: float = 10.0     # ref :23
    rate_limit_wait: float = 200.0      # ref :28
    page_load_timeout: float = 30.0     # ref :139
    ready_state_timeout: float = 10.0   # ref :151
    result_timeout: float = 60.0        # ref :439
    transport: str = "auto"  # auto|selenium|firefox-wire|chrome-wire|
    #   stealth-chrome|requests|mock ("auto" = selenium → firefox-wire →
    #   requests; the wire flavours need only a driver binary, no selenium)
    out_dir: str = "."


@dataclass(frozen=True)
class HarvestConfig:
    """CDX URL-discovery shard sweep (ref yahoo_links_selenium.py:19-34)."""

    num_workers: int = 10               # ref :19
    shard_dir: str = "yahoo_links_1"    # ref :29
    output_csv: str = "yfin_urls.csv"   # ref :178
    cdx_base: str = "http://web.archive.org/cdx/search/"
    target_pattern: str = "https://www.finance.yahoo.com/news/{prefix}*"
    ready_state_timeout: float = 3.0    # ref :43
    transport: str = "auto"


@dataclass(frozen=True)
class EnrichConfig:
    """Wikidata SPARQL enrichment (ref ticker_symbol_query*.py)."""

    endpoint: str = "https://query.wikidata.org/sparql"
    symbols_csv: str = "sp500list.csv"  # ref ticker_symbol_query.py:196
    out_dir: str = "info/ticker"        # ref :191
    hardened: bool = True
    max_retries: int = 5                # ref protected :34
    base_delay: float = 5.0             # ref protected :34
    connect_timeout: float = 15.0       # ref protected :212
    read_timeout: float = 60.0          # ref protected :212
    progress_file: str = "progress.json"  # ref protected :340
    crypto_symbols_csv: str = "crypto_list.csv"   # crypto flow symbol source
    crypto_out_dir: str = "info/crypto"           # beside info/ticker (SURVEY §L4)
    crypto_progress_file: str = "progress_crypto.json"
    cooldown_every3: tuple = (15.0, 25.0)   # ref protected :419-421
    cooldown_every10: tuple = (60.0, 120.0)  # ref protected :423-426


@dataclass(frozen=True)
class MatchConfig:
    """Entity→article matching (ref match_keywords.py)."""

    source_name: str = "yahoo"          # ref :222
    info_dir: str = "info/Icahn_filter"  # ref :223
    articles_csv: str = "datasets/yahoo_articles_all.csv"
    chunk_size: int = 20000             # ref :227
    fuzzy_threshold: float = 95.0       # ref :175 (partial_ratio > 95)
    use_tpu: bool = True
    out_dir_suffix: str = "_ticker_matched_articles"  # ref :129
    verify_workers: int = 0  # exact-verify process fan-out; 0 = cpu_count
    #                          (the ref's mp.Pool width, :231-238); 1 = inline
    packed: bool = True      # screen tiles cross H2D as ONE packed buffer
    #   (ops/pack.py, SCREEN_PLANES trailer) into ONE fused jitted screen
    #   (+Myers-bound) dispatch (ops.match.make_screen_step), pipelined
    #   through the dispatch executor — 1 put + 1 dispatch per tile.
    #   ASTPU_MATCH_PACKED=0 restores the legacy per-batch screen loop
    #   (multiple puts + screen-then-bound dispatches), kept byte-identical
    #   for parity certification and as an escape hatch.
    dispatch_window: int = 0  # depth-N in-flight screen-tile window in the
    #   pipelined executor (staged-edge capacity; 0 = auto:
    #   max(2, put_workers) — same semantics as the dedup knob)
    put_workers: int = 0     # H2D put threads for screen tiles (0 = the
    #   transport default, core.mesh.auto_h2d_workers — 4 on the
    #   serializing axon tunnel, 1 on local backends)
    screen_tile_bytes: int = 1 << 21  # byte budget per packed screen tile:
    #   rows-per-tile ≈ budget // row width (power-of-two bucketed, like the
    #   dedup encoder) — replaces the retired fixed screen_batch=128 tile
    #   sizing (MIGRATION.md), so narrow news corpora screen thousands of
    #   rows per dispatch while 64 kB rows still tile shallowly
    prewarm: int = 0         # compile the packed screen-step shape set at
    #   run start (pipeline.matcher.prewarm_screen): every width bucket's
    #   full tile plus its power-of-two tail chunks, screen-only AND fused
    #   modes.  0 = off (tests must not pay the compile set); pair with
    #   ASTPU_COMPILE_CACHE to make the warmup survive restarts


@dataclass(frozen=True)
class DedupConfig:
    """MinHash+LSH near-dup engine (BASELINE.json north star)."""

    shingle_k: int = 5       # k=5 byte shingles
    num_perm: int = 128      # 128 permutations
    num_bands: int = 16      # 16-band LSH
    block_len: int = 4096    # bytes per device block (bucketed padding)
    batch_size: int = 1024
    sim_threshold: float = 0.70  # signature-agreement verification threshold
    cand_subbands: int = 32  # extra fine candidate bands (128/32 = 4 rows:
    #   near-certain candidacy at the threshold knee; 0 disables.  Merges
    #   still require signature-agreement verification.
    fine_margin: float = 0.0  # extra estimator bar on FINE-ONLY edges
    #   (candidate pairs sharing no coarse band — outside datasketch's
    #   candidacy class; ops.lsh.fine_edge_thresholds) in the paths that
    #   CANNOT exact-verify (async firehose, streaming backend — old-side
    #   texts are gone there).  Estimator-only margins cannot meet the
    #   precision budget (measured frontier: tools/sweep_fine_margin.py);
    #   the certified one-shot path uses exact_verify_band instead.
    exact_verify_band: float = 0.72  # one-shot dedup_reps: every fine-only
    #   edge, and every coarse edge with agreement < this band, is
    #   confirmed by EXACT shingle-set Jaccard on host before resolution
    #   (borderline estimator verdicts are noise, σ≈0.04 at 128 perms).
    #   Measured (DESIGN.md §2e): recall 0.952, precision oracle+0.01 on
    #   the hardened corpus at ~130 exact checks per 2048 docs.  0 disables.
    exact_verify_cap: int = 8192  # max exact-Jaccard checks per corpus —
    #   beyond it remaining borderline edges keep their estimator verdict
    #   (a pathological all-borderline corpus must not degrade to O(n²))
    rerank: bool = True      # install the device-batched precision tier
    #   (pipeline/rerank.py) on RERANK_HOOK_EDGE at engine init: candidate
    #   pairs are settled by a vmap'd bottom-sketch Jaccard kernel in
    #   packed device tiles (1 put + 1 dispatch per tile through the
    #   dispatch executor, verdicts folded on-device and read back once
    #   per corpus), then clusters are precision-evicted to the ≥0.95 bar.
    #   ASTPU_DEDUP_RERANK=0 opts out (rerank_hook=None, the pre-tier
    #   hookless paths, byte-identical); the skip_rerank brownout bypasses
    #   it counted-and-reversibly without uninstalling.
    rerank_sketch: int = 1024  # bottom-S sketch lanes per document: the
    #   settle estimator's σ≈√(J(1−J)/S) (≈0.014 at 1024, 3× tighter than
    #   the 128-perm signature) and EXACT when |shingle union| ≤ S.  Pair
    #   rows are 8·S bytes on the wire; 2·S lanes per sort keeps the
    #   kernel aligned to 128-lane tiles.
    rerank_margin: float = 0.04  # half-width of the borderline band
    #   around sim_threshold: settled pairs with |J − thr| < margin are
    #   re-settled on host (exact shingle Jaccard up to rerank_exact_cap,
    #   then the persistent index's ANN re-probe when attached, else the
    #   sketch verdict stands).  ~3σ of the sketch estimator.
    rerank_precision_target: float = 0.96  # predicted merged-pair
    #   precision the greedy eviction walk stops at (ops.rerank.
    #   evict_for_precision; measured 5-seed operating points: pooled
    #   0.981 recall / 0.961 precision on the representative mix, and
    #   0.963 / 0.928 — a strict Pareto win over the hookless baseline's
    #   0.952 / 0.921 — on the adversarial knee-heavy suite, where the
    #   recall floor binds before the target is reached)
    rerank_recall_floor: float = 0.955  # hard predicted-recall guard:
    #   eviction never crosses below this fraction of the candidate
    #   work-list's expected oracle-recall mass (ops.rerank.op_weight
    #   prices each settled pair's probability of being counted by the
    #   estimator oracle), keeping the measured ≥0.95 recall bar with
    #   margin for estimator drift — on adversarial mixes this floor,
    #   not the target, is what stops eviction
    rerank_exact_cap: int = 8192  # max host exact-Jaccard re-settles per
    #   corpus (borderline band + wave-2 residue); beyond it borderline
    #   pairs fall to the ANN re-probe / sketch verdict — a pathological
    #   all-borderline corpus must not degrade to O(n²) host work
    rerank_tile_rows: int = 1024  # pair rows per full settle tile; the
    #   tile shape set is tile_rows_options(rerank_tile_rows) — shared
    #   with the engine prewarm derivation so the PR 15 recompile
    #   sentinel stays zero in steady state
    rerank_pair_cap: int = 1 << 16  # fold-buffer slots: max device-settled
    #   pairs per corpus (256 KiB int32 on device).  Overflow pairs keep
    #   their estimator verdict and are counted in the tier stats.
    seed: int = 1            # datasketch's default seed for oracle parity
    backend: str = "scan"    # scan (dense, datasketch-parity) | oph | pallas
    put_workers: int = 0     # H2D put threads INSIDE the pipelined
    #   dispatch executor (pipeline/dispatch.py — the encode→pack→put→
    #   dispatch pipeline every signature corpus now rides).
    #   0 = auto: the transport default (core.mesh.auto_h2d_workers — 4 on
    #   the serializing axon tunnel, 1 on local backends); >1 overlaps
    #   per-put round trips (DESIGN §5 stream-tuning note);
    #   order-independent min-combine makes any arrival order exact.
    #   Pre-PR-9 this knob also selected the inline put→accumulate loop
    #   at 1 — the executor is now always on (MIGRATION.md).
    dispatch_window: int = 0  # depth-N in-flight dispatch window: tiles
    #   resident between the H2D put stage and the accumulate dispatch
    #   (the executor's staged-edge capacity; total in-flight device
    #   tiles ≈ window + put_workers + 1 accumulating).  0 = auto:
    #   max(2, put_workers) — double buffering on local backends, a
    #   put-worker-deep window on serializing transports.
    packed_h2d: bool = True  # pack each tile's (tokens, lengths, owners)
    #   into ONE contiguous buffer (ops/pack.py): per-tile H2D is one
    #   device_put instead of three serialized round trips, and the
    #   signature+accumulate step is ONE fused jitted dispatch with the
    #   accumulator donated (ops.minhash.make_fused_tile_step).  False
    #   restores the legacy 3-put/2-dispatch tile transport — kept for
    #   parity certification (byte-identical, tested) and as an escape
    #   hatch; both routes ride the same executor.
    prewarm: int = 0         # compile the packed tile-step shape set at
    #   engine init (NearDupEngine.prewarm): every width bucket's full
    #   tile plus its O(log bs) power-of-two tail chunks.  0 = off
    #   (default: cold compile of the full set costs tens of seconds on
    #   CPU, which tests must not pay); 1 = prewarm for one batch_size
    #   corpus; >1 = the EXPECTED ARTICLE COUNT per corpus — the fused
    #   step is compiled per bucketed article axis, so prewarming the
    #   wrong bucket recompiles everything on the first real corpus
    #   anyway.  Pair with ASTPU_COMPILE_CACHE (persistent XLA
    #   compilation cache) to make the warmup survive process restarts.
    stream_index: str = "exact"  # exact (attributed, grows with stream) |
    #   bloom (LSHBloom: fixed memory, no attribution) |
    #   persist (index/ subsystem: durable log-structured postings on disk,
    #   bounded resident memory, doc-id attribution, cross-RUN dedup)
    bloom_bits: int = 1 << 24    # bits per band filter (bloom mode)
    bloom_hashes: int = 4
    index_dir: str = ""          # persist mode: postings directory ("" →
    #   the caller derives one, e.g. the scraper uses
    #   <out_dir>/stream_index_<website>/)
    index_cut_postings: int = 1 << 16  # persist mode: memtable postings per
    #   segment cut (the WAL→segment cadence; RAM between cuts is bounded
    #   by this × ~80 B)
    index_compact_segments: int = 8    # persist mode: live-segment count
    #   that triggers background compaction (0 disables)
    index_fleet: str = ""        # persist mode: remote index fleet spec
    #   ("host:port|host:port;host:port|..." — ';' separates shards, '|'
    #   separates a shard's primary/replica; see index/fleet.py).  Empty =
    #   local single-node PersistentIndex (the PR 4 behaviour).  When set,
    #   the stream_index="persist" path talks to IndexShardServer nodes
    #   through ShardedIndexClient: consistent-hashed band-key space,
    #   synchronous replication, lease-TTL-style failover with
    #   health-checked promotion, journaled local spill when a shard is
    #   fully dark.
    index_fleet_timeout: float = 5.0   # per-RPC deadline (seconds)
    index_fleet_retries: int = 2       # transport retries per call (same
    #   request id; the shard's idempotency nets make redelivery safe)
    index_fleet_health_checks: int = 2  # consecutive pings a replica must
    #   answer before being promoted to write target
    ckpt_every_batches: int = 16  # stream-index checkpoint cadence, in
    #   device batches: the scraper persists the dedup index every N
    #   processed batches (persist: WAL fsync + due segment cut — O(new
    #   postings); exact/bloom: a FULL atomic npz rewrite — O(index), so
    #   raise N as the corpus grows, or 0 to checkpoint only at run end,
    #   the pre-knob behaviour) — previously an inline end-of-run-only
    #   constant in pipeline/scraper.py


@dataclass(frozen=True)
class MeshConfig:
    """Device mesh layout (v5e-8 target: 8 chips, 1 axis of data parallelism
    plus optional sequence-parallel axis for long articles)."""

    data_axis: str = "data"
    seq_axis: str = "seq"
    data_parallel: int = -1  # -1: all devices
    seq_parallel: int = 1


@dataclass(frozen=True)
class FeedConfig:
    """Host feed scheduler / distributed lease protocol
    (ref server1.py:20,102-138, client1.py:17-24,209-234)."""

    host: str = "localhost"
    port: int = 8000                  # ref server1.py:18
    max_clients: int = 5              # ref server1.py:20
    batch_size: int = 20              # ref client1.py:23
    min_queue_length: int = 10        # ref client1.py:24
    client_threads: int = 8           # ref client1.py:21
    client_rate: float = 8.0          # ref client1.py:18
    lease_ttl: float = 30.0           # seconds without any complete frame
    #   (heartbeats count) before a client's leases are requeued and its
    #   connection cut — a hung-but-connected worker must not strand its
    #   urls until TCP notices.  0 disables (disconnect-only reclaim, the
    #   pre-fleet behaviour).
    heartbeat_interval: float = 0.0   # client heartbeat cadence; 0 = auto
    #   (lease_ttl / 4, never more than once a second of idleness)
    max_frame_bytes: int = 16 << 20   # NDJSON line-reassembly cap: a peer
    #   that never sends a newline is cut off here instead of growing the
    #   buffer without bound (the drop is counted in telemetry)
    connect_retries: int = 5          # LeaseClient initial-connect attempts
    connect_backoff: float = 0.05     # backoff base (capped exponential
    #   with jitter, cap 2 s) between connect attempts


@dataclass(frozen=True)
class Config:
    scraper: ScraperConfig = field(default_factory=ScraperConfig)
    harvest: HarvestConfig = field(default_factory=HarvestConfig)
    enrich: EnrichConfig = field(default_factory=EnrichConfig)
    match: MatchConfig = field(default_factory=MatchConfig)
    dedup: DedupConfig = field(default_factory=DedupConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    feed: FeedConfig = field(default_factory=FeedConfig)

    def replace(self, **kw: Any) -> "Config":
        return dataclasses.replace(self, **kw)


def default_config() -> Config:
    return Config(
        scraper=from_env(ScraperConfig, "scraper"),
        harvest=from_env(HarvestConfig, "harvest"),
        enrich=from_env(EnrichConfig, "enrich"),
        match=from_env(MatchConfig, "match"),
        dedup=from_env(DedupConfig, "dedup"),
        mesh=from_env(MeshConfig, "mesh"),
        feed=from_env(FeedConfig, "feed"),
    )
