"""Filesystem seam + deterministic storage fault injection.

The reference's resilience model is output-artifact-as-checkpoint
(SURVEY.md §5.4): CSVs, shard files and the stream-index npz ARE the
recovery state.  Yet nothing in the reference — or in this reproduction
before this module — could *test* what a faulty substrate does to those
artifacts: short writes, EIO on flush, fsync failure and crash-mid-write
are the dominant real-world failure mode for append-style checkpoints
(VERDICT.md §"What's missing").

This module is the storage twin of ``net.transport.ChaosTransport``:

- :class:`OsFs` — the real substrate (thin ``os``/``open`` veneer).  Every
  persistence site (``storage/csvio.py``, ``pipeline/harvest.py``,
  ``extractors/tpu_batch.py``) goes through an ``fs`` object with this
  surface, so fault injection threads in without touching call sites.
- :class:`ChaosFs` / :class:`ChaosFile` — seeded, reproducible fault
  injection with the same determinism contract as ``ChaosTransport``:
  fault assignment is a pure function of ``(seed, path, per-path op
  index)``, NOT a shared random stream, so a given operation faults
  identically on every run with the same seed and the ``ledger`` is
  byte-for-byte reproducible even under thread nondeterminism.
- :func:`atomic_replace` — the torn-write-safe persistence primitive
  (tmp + flush + fsync + rename): a crash at ANY byte leaves the target
  either byte-complete or untouched, never torn.
- :func:`default_fs` — process default, overridable via the
  ``ASTPU_CHAOS_FS`` env spec so *forked children* (the kill-restart
  harness, ``tools/crashsweep.py``) inherit injection without plumbing.
"""

from __future__ import annotations

import errno
import io
import os
import threading
import time

__all__ = [
    "OsFs",
    "ChaosFs",
    "ChaosFile",
    "SimulatedCrash",
    "atomic_replace",
    "atomic_write",
    "default_fs",
    "set_default_fs",
]


class SimulatedCrash(BaseException):
    """Raised by the crash-after-N-bytes fault (in-process flavour).

    A ``BaseException`` on purpose: production code catching broad
    ``Exception`` for per-item containment must NOT swallow a simulated
    process death — the whole point is that nothing downstream of the
    crash point runs, exactly like SIGKILL.  (Child processes under the
    crashsweep driver use ``exit=1`` in the env spec instead, which calls
    ``os._exit`` — a real no-cleanup death.)
    """


class OsFs:
    """The real filesystem, behind the seam every persistence site uses."""

    def open(self, path: str, mode: str = "r", **kw):
        return open(path, mode, **kw)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def size(self, path: str) -> int:
        return os.stat(path).st_size

    def replace(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def remove(self, path: str) -> None:
        os.unlink(path)

    def fsync(self, fh) -> None:
        os.fsync(fh.fileno())

    def fsync_dir(self, path: str) -> None:
        """Best-effort directory fsync after a rename — required for the
        rename itself to be durable on POSIX, silently skipped where
        directories cannot be opened (e.g. some overlay mounts)."""
        try:
            fd = os.open(os.path.dirname(os.path.abspath(path)) or ".", os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)


class ChaosFile:
    """Fault-injecting proxy around one open file object.

    Write-side faults only — reads pass through (torn *tails* are created
    by faulted writes and crashes; the read-side contract is the torn-tail
    repair in ``storage/csvio.py``).  Fault kinds:

    - ``short_write``: persist a strict prefix of the buffer, then raise
      ``EIO`` — the torn-tail generator (a real ``write(2)`` can persist
      fewer bytes than asked before the error).
    - ``eio_flush``: ``flush()`` raises ``EIO`` without flushing.
    - ``crash``: persist a prefix, flush it, then die (``SimulatedCrash``
      in-process; ``os._exit`` under ``exit=1``) — crash-after-N-bytes.
    - ``bitflip``: SILENTLY flip one seeded bit of the buffer and persist
      the rest intact — no error, no short count: the medium lied.  The
      bit-rot generator the integrity plane (segment block CRCs, scrub,
      ``tools/fsck_index.py``) exists to catch; binary writes only (a
      text-mode write passes through unfaulted and uncounted).
    """

    def __init__(self, inner, fs: "ChaosFs", path: str):
        self._inner = inner
        self._fs = fs
        self._path = path

    # -- faulted surface ---------------------------------------------------

    def write(self, data):
        binary = isinstance(data, (bytes, bytearray, memoryview))
        kind = self._fs._decide(self._path, "write", binary=binary)
        if kind == "bitflip":
            # silent corruption: the write "succeeds" byte-for-byte except
            # one seeded flipped bit — exactly what a rotting medium does
            return self._inner.write(self._fs._flip_bit(self._path, bytes(data)))
        if kind in ("short_write", "crash"):
            # persist a deterministic strict prefix — the byte count comes
            # from the same seeded stream as the fault decision
            n = self._fs._prefix_len(self._path, len(data))
            self._inner.write(data[:n])
            self._inner.flush()
            if kind == "crash":
                self._fs._die(self._path, "write")
            raise OSError(
                errno.EIO,
                f"injected short write ({n}/{len(data)} bytes) for {self._path}",
            )
        return self._inner.write(data)

    def flush(self):
        kind = self._fs._decide(self._path, "flush")
        if kind == "eio_flush":
            raise OSError(errno.EIO, f"injected flush failure for {self._path}")
        if kind == "crash":
            self._fs._die(self._path, "flush")
        return self._inner.flush()

    # -- passthrough -------------------------------------------------------

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._inner.close()

    def __iter__(self):
        return iter(self._inner)


class ChaosFs:
    """Deterministic fault injection around any inner fs backend.

    Mirrors :class:`net.transport.ChaosTransport`: every fault decision is
    a pure function of ``(seed, path, per-path op index)`` — two runs with
    the same seed executing the same operation sequence produce an
    identical ``ledger`` (the reproducibility contract the crash sweep
    asserts).  ``injected`` counts faults by kind; ``ledger`` records
    ``(path, op, kind)`` in fire order.

    ``only`` restricts injection to paths containing the substring — e.g.
    fault only the success CSV, leaving fixture reads untouched.
    """

    #: fault kinds, in decision order (one uniform draw per kind, like
    #: ChaosTransport's rate cascade; ``bitflip`` sits LAST so enabling it
    #: never shifts the draw sequence of pre-existing seeded specs)
    KINDS = ("short_write", "eio_flush", "fsync_error", "crash", "bitflip")

    def __init__(
        self,
        inner=None,
        *,
        seed: int = 0,
        short_write_rate: float = 0.0,
        eio_flush_rate: float = 0.0,
        fsync_error_rate: float = 0.0,
        crash_rate: float = 0.0,
        bitflip_rate: float = 0.0,
        only: str | None = None,
        on_crash=None,
    ):
        self._inner = inner or OsFs()
        self._seed = seed
        self._rates = {
            "short_write": short_write_rate,
            "eio_flush": eio_flush_rate,
            "fsync_error": fsync_error_rate,
            "crash": crash_rate,
            "bitflip": bitflip_rate,
        }
        self._only = only
        self._on_crash = on_crash  # None → raise SimulatedCrash
        self._lock = threading.Lock()
        self._op_counts: dict[tuple[str, str], int] = {}
        self.injected: dict[str, int] = {k: 0 for k in self.KINDS}
        self.ledger: list[tuple[str, str, str]] = []

    # -- decision machinery ------------------------------------------------

    def _rng(self, path: str, op: str, n: int):
        import random

        # string-seeded Random hashes its bytes (sha512): stable across
        # processes and threads, like ChaosTransport's (seed, url) scheme
        return random.Random(f"{self._seed}|{os.path.basename(path)}|{op}|{n}")

    def _decide(self, path: str, op: str, *, binary: bool = True) -> str | None:
        if self._only is not None and self._only not in path:
            return None
        with self._lock:
            key = (os.path.basename(path), op)
            n = self._op_counts.get(key, 0)
            self._op_counts[key] = n + 1
        r = self._rng(path, op, n).random
        for kind in self.KINDS:
            if self._rates[kind] and r() < self._rates[kind]:
                if kind == "bitflip" and not binary:
                    return None  # flip is defined on bytes only
                if (kind, op) in _KIND_OPS:
                    with self._lock:
                        self.injected[kind] += 1
                        self.ledger.append((os.path.basename(path), op, kind))
                    # fault counts belong on /metrics, not only in the
                    # ledger object a test happens to hold (always-on:
                    # injection is rare by construction)
                    from advanced_scrapper_tpu.obs import telemetry

                    telemetry.event_counter(
                        "astpu_fault_injected_total",
                        "chaos faults fired, by plane and kind",
                        plane="fs",
                        kind=kind,
                    ).inc()
                    return kind
                return None  # kind drawn but not applicable to this op
        return None

    def _flip_bit(self, path: str, data: bytes) -> bytes:
        """One seeded bit flipped in ``data`` — same determinism contract
        as every other fault: a pure function of (seed, path, per-path
        flip index)."""
        if not data:
            return data
        with self._lock:
            key = (os.path.basename(path), "bitflip")
            n = self._op_counts.get(key, 0)
            self._op_counts[key] = n + 1
        bit = self._rng(path, "bitflip", n).randrange(len(data) * 8)
        out = bytearray(data)
        out[bit // 8] ^= 1 << (bit % 8)
        return bytes(out)

    def _prefix_len(self, path: str, total: int) -> int:
        if total <= 1:
            return 0
        with self._lock:
            key = (os.path.basename(path), "prefix")
            n = self._op_counts.get(key, 0)
            self._op_counts[key] = n + 1
        return self._rng(path, "prefix", n).randrange(1, total)

    def _die(self, path: str, op: str):
        # last act before death: dump the flight recorder so the sweep
        # harness can assert on what was in flight at the kill point
        # (covers BOTH flavours — os._exit runs no cleanup handlers, and
        # SimulatedCrash is a BaseException production code must not catch)
        try:
            from advanced_scrapper_tpu.obs import trace

            trace.dump_on_fault(f"chaos-fs crash during {op} of {path}")
        except Exception:
            pass
        if self._on_crash is not None:
            self._on_crash()
        raise SimulatedCrash(f"injected crash during {op} of {path}")

    # -- fs surface --------------------------------------------------------

    def open(self, path: str, mode: str = "r", **kw):
        fh = self._inner.open(path, mode, **kw)
        if any(m in mode for m in ("w", "a", "+", "x")):
            return ChaosFile(fh, self, path)
        return fh

    def exists(self, path: str) -> bool:
        return self._inner.exists(path)

    def size(self, path: str) -> int:
        return self._inner.size(path)

    def replace(self, src: str, dst: str) -> None:
        kind = self._decide(dst, "replace")
        if kind == "crash":
            self._die(dst, "replace")
        self._inner.replace(src, dst)

    def remove(self, path: str) -> None:
        self._inner.remove(path)

    def fsync(self, fh) -> None:
        target = getattr(fh, "name", "<fh>")
        kind = self._decide(str(target), "fsync")
        if kind == "fsync_error":
            raise OSError(errno.EIO, f"injected fsync failure for {target}")
        if kind == "crash":
            self._die(str(target), "fsync")
        inner = getattr(fh, "_inner", fh)
        self._inner.fsync(inner)

    def fsync_dir(self, path: str) -> None:
        self._inner.fsync_dir(path)


#: which fault kinds apply to which operation — a draw of an inapplicable
#: kind is a no-fault (keeps each op's decision a single-seeded function
#: instead of per-op rate vocabularies)
_KIND_OPS = {
    ("short_write", "write"),
    ("crash", "write"),
    ("bitflip", "write"),
    ("eio_flush", "flush"),
    ("crash", "flush"),
    ("fsync_error", "fsync"),
    ("crash", "fsync"),
    ("crash", "replace"),
}


#: dir → leftover ``*.tmp-*`` names found by the once-per-process scandir
#: (a listing per atomic_write would be O(dir) on every persist — a full
#: harvest writes thousands of files into one shard_dir)
_stale_tmps: dict[str, set[str]] = {}
_stale_lock = threading.Lock()


def _sweep_stale_tmps(path: str, own_tmp: str, fs) -> None:
    """Remove tmp orphans left by CRASHED writers of ``path``: their pids
    differ, so the writer's own cleanup never matches them, and a long
    deployment of kill-restart cycles would otherwise grow the directory
    unboundedly (the single-writer model makes any same-path tmp with a
    foreign pid stale by definition).  The directory is listed once per
    process — orphans only ever predate it."""
    dirname = os.path.dirname(os.path.abspath(path)) or "."
    with _stale_lock:
        found = _stale_tmps.get(dirname)
        if found is None:
            found = set()
            try:
                with os.scandir(dirname) as it:
                    for entry in it:
                        if ".tmp-" in entry.name:
                            found.add(entry.name)
            except OSError:
                pass
            _stale_tmps[dirname] = found
        prefix = os.path.basename(path) + ".tmp-"
        mine = [n for n in found if n.startswith(prefix)]
        found.difference_update(mine)
    for name in mine:
        stale = os.path.join(dirname, name)
        if stale != own_tmp:
            try:
                fs.remove(stale)
            except OSError:
                pass


def atomic_write(path: str, writer, fs=None) -> None:
    """Torn-write-safe whole-file persistence: tmp + flush + fsync + rename.

    ``writer(fh)`` streams the payload into the tmp handle (so large
    artifacts — e.g. a compressed npz of all kept signatures — never need
    a second in-memory copy).  The rename is the commit point — a crash
    at any earlier byte leaves ``path`` untouched (tmp garbage is
    re-created/cleaned on retry, and stale tmps are invisible to every
    reader).  This is the primitive behind shard files and the
    stream-index checkpoint; append-style CSVs use torn-tail repair
    instead (``storage/csvio.py``).
    """
    fs = fs or default_fs()
    tmp = f"{path}.tmp-{os.getpid()}"
    _sweep_stale_tmps(path, tmp, fs)
    try:
        with fs.open(tmp, "wb") as fh:
            writer(fh)
            fh.flush()
            fs.fsync(fh)
        fs.replace(tmp, path)
    except SimulatedCrash:
        # a simulated death leaves its torn tmp behind, exactly like a
        # real SIGKILL would — readers must prove they never look at it
        raise
    except BaseException:
        # ordinary failures (EIO, fsync error) clean their tmp so retries
        # never see garbage
        try:
            if fs.exists(tmp):
                fs.remove(tmp)
        except OSError:
            pass
        raise
    fs.fsync_dir(path)


def atomic_replace(path: str, data: bytes, fs=None) -> None:
    """:func:`atomic_write` for callers whose payload is already bytes."""
    atomic_write(path, lambda fh: fh.write(data), fs=fs)


# -- process default -------------------------------------------------------

_default_lock = threading.Lock()
_default_fs = None


def _parse_env_spec(spec: str):
    """``ASTPU_CHAOS_FS="seed=7,short_write=0.05,eio_flush=0.02,fsync=0.02,
    crash=0.01,exit=1,only=success"`` → a configured :class:`ChaosFs`.

    ``exit=1`` makes the crash fault call ``os._exit(73)`` — a real
    no-cleanup process death for forked children under the kill-restart
    harness (in-process callers get :class:`SimulatedCrash` instead).
    """
    kw: dict = {}
    on_crash = None
    only = None
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        k = k.strip()
        v = v.strip()
        if k == "seed":
            kw["seed"] = int(v)
        elif k == "short_write":
            kw["short_write_rate"] = float(v)
        elif k == "eio_flush":
            kw["eio_flush_rate"] = float(v)
        elif k == "fsync":
            kw["fsync_error_rate"] = float(v)
        elif k == "crash":
            kw["crash_rate"] = float(v)
        elif k == "bitflip":
            kw["bitflip_rate"] = float(v)
        elif k == "only":
            only = v
        elif k == "exit":
            if v not in ("0", "", "false"):
                on_crash = lambda: os._exit(73)  # noqa: E731
        else:
            raise ValueError(f"unknown ASTPU_CHAOS_FS key {k!r}")
    return ChaosFs(OsFs(), only=only, on_crash=on_crash, **kw)


def default_fs():
    """The process-wide fs backend every persistence site defaults to.

    Plain :class:`OsFs` unless ``ASTPU_CHAOS_FS`` is set (evaluated once,
    at first use) or a test installed one via :func:`set_default_fs`.
    """
    global _default_fs
    with _default_lock:
        if _default_fs is None:
            spec = os.environ.get("ASTPU_CHAOS_FS", "")
            _default_fs = _parse_env_spec(spec) if spec else OsFs()
        return _default_fs


def set_default_fs(fs) -> None:
    """Install (or with ``None``, reset) the process default — the hook
    tests use to thread :class:`ChaosFs` under engines without touching
    their call signatures."""
    global _default_fs
    with _default_lock:
        _default_fs = fs
