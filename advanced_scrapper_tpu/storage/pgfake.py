"""psycopg2-compatible DBAPI fake with REAL transactional semantics.

This environment has neither a Postgres server nor psycopg2, yet the
reference's crypto pollers are Postgres-first (``CREATE DATABASE``
bootstrap + ``INSERT … ON CONFLICT DO NOTHING``,
``/root/reference/experiental/04_crypto_1.py:14-34,76-80``).  To keep
:class:`~advanced_scrapper_tpu.storage.backends.PostgresBackend` honest
beyond object stubs, this module emulates the psycopg2 surface the stores
use — module ``connect()``, connections with ``autocommit`` /
``commit()`` / ``rollback()`` / context-manager transaction blocks,
cursors with ``rowcount`` — over per-database sqlite files in WAL mode
(temp-dir backed, removed on ``close()``), with the Postgres dialect
translated per statement:

- ``%s`` placeholders → ``?`` — textually, EVERY occurrence: a literal
  ``%s`` inside a quoted string constant or LIKE pattern would be
  rewritten too (none of the store surface does this; revisit with a
  quote-aware scanner if store SQL grows string literals);
- ``SELECT … FROM pg_database WHERE datname = %s`` → the server registry;
- ``CREATE DATABASE "x"`` → a new shared in-memory database, refused
  inside a transaction exactly like the real server
  (psycopg2 ``ActiveSqlTransaction``);
- ``SELECT … FROM information_schema.tables WHERE table_name = %s`` →
  ``sqlite_master``.

Transactions are genuine: with ``autocommit = False`` (the DBAPI default)
writes stay invisible to other connections until ``commit()``, and
``rollback()`` discards them — the semantics the store's
one-transaction-per-operation contract (``stores.py::_StoreBase._conn``)
relies on.  Every connection to the same DSN database name sees one shared
database, so separate store operations round-trip like they would against
a server.

This is an offline stand-in, not a Postgres implementation: only the
dialect surface above is translated.  Against a real server the same
store code runs through psycopg2 unchanged.
"""

from __future__ import annotations

import re
import sqlite3
import threading


class Error(Exception):
    """DBAPI base error (psycopg2.Error shape)."""


class ProgrammingError(Error):
    pass


class ActiveSqlTransaction(ProgrammingError):
    """CREATE DATABASE inside a transaction — refused like the server."""


class OperationalError(Error):
    """Connecting to a database that does not exist."""


class FakePostgresServer:
    """Registry of named databases ("the server").

    Each database is one sqlite file in WAL mode inside a private temp
    dir: WAL gives Postgres-like snapshot behaviour — readers on other
    connections see the last COMMITTED state while a writer's transaction
    is open, instead of shared-cache sqlite's table-level read locks.
    """

    def __init__(self):
        import tempfile

        self._dir = tempfile.mkdtemp(prefix="pgfake-")
        self._dbs: set[str] = set()
        self._lock = threading.Lock()
        self.ensure("postgres")  # the admin database always exists

    def _path(self, name: str) -> str:
        import os

        return os.path.join(self._dir, f"{name}.db")

    def ensure(self, name: str) -> None:
        with self._lock:
            if name not in self._dbs:
                conn = sqlite3.connect(self._path(name))
                conn.execute("PRAGMA journal_mode=WAL")
                conn.close()
                self._dbs.add(name)

    def exists(self, name: str) -> bool:
        with self._lock:
            return name in self._dbs

    def close(self) -> None:
        import shutil

        with self._lock:
            self._dbs.clear()
            shutil.rmtree(self._dir, ignore_errors=True)

    # -- DBAPI module surface (inject the server itself as the driver) ----
    paramstyle = "pyformat"

    def connect(self, dsn: str):
        name = dbname_from_dsn(dsn)
        if not self.exists(name):
            raise OperationalError(f'database "{name}" does not exist')
        raw = sqlite3.connect(
            self._path(name), check_same_thread=False, timeout=10.0
        )
        return FakeConnection(raw, self)


def dbname_from_dsn(dsn: str) -> str:
    """Database name from a ``postgresql://…/dbname`` URL or a
    ``dbname=x host=y`` keyword DSN (both psycopg2 forms)."""
    m = re.search(r"dbname\s*=\s*(\S+)", dsn)
    if m:
        return m.group(1)
    m = re.match(r"postgres(?:ql)?://[^/]*/([^/?\s]+)", dsn)
    if m:
        return m.group(1)
    return "postgres"


_CREATE_DB = re.compile(r'^\s*CREATE\s+DATABASE\s+"?([A-Za-z0-9_]+)"?\s*$', re.I)
_PG_DATABASE = re.compile(r"\bpg_database\b", re.I)
_INFO_TABLES = re.compile(r"\binformation_schema\.tables\b", re.I)


class FakeConnection:
    def __init__(self, raw: sqlite3.Connection, server: FakePostgresServer):
        # isolation handled here, not by the sqlite3 module: BEGIN on the
        # first statement of a transaction, so autocommit toggling and
        # commit/rollback visibility behave like psycopg2
        raw.isolation_level = None
        self._raw = raw
        self._server = server
        self._closed = False
        self._in_txn = False
        self.autocommit = False

    # psycopg2's `with conn:` commits/rolls back but does NOT close
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.commit()
        else:
            self.rollback()
        return False

    def cursor(self):
        if self._closed:
            raise Error("connection already closed")
        return FakeCursor(self)

    def _begin_if_needed(self) -> None:
        if not self.autocommit and not self._in_txn:
            self._raw.execute("BEGIN")
            self._in_txn = True

    def commit(self) -> None:
        if self._in_txn:
            self._raw.execute("COMMIT")
            self._in_txn = False

    def rollback(self) -> None:
        if self._in_txn:
            self._raw.execute("ROLLBACK")
            self._in_txn = False

    def close(self) -> None:
        if not self._closed:
            # psycopg2 discards an open transaction on close
            self.rollback()
            self._raw.close()
            self._closed = True


class FakeCursor:
    def __init__(self, conn: FakeConnection):
        self._conn = conn
        self._cur = conn._raw.cursor()
        self.rowcount = -1

    def execute(self, sql: str, params=()):
        conn = self._conn
        if conn._closed:
            raise Error("connection already closed")

        m = _CREATE_DB.match(sql)
        if m:
            if not conn.autocommit:
                # server behaviour: CREATE DATABASE cannot run inside a
                # transaction block (the bootstrap code must set
                # autocommit first, ref backends.py::ensure_database)
                raise ActiveSqlTransaction(
                    "CREATE DATABASE cannot run inside a transaction block"
                )
            conn._server.ensure(m.group(1))
            self.rowcount = -1
            return self

        translated = sql.replace("%s", "?")
        if _PG_DATABASE.search(translated):
            name = params[0] if params else None
            self._rows = [(1,)] if name and conn._server.exists(name) else []
            # psycopg2 reports the SELECT's row count, not -1
            self.rowcount = len(self._rows)
            self._from_list = True
            return self
        self._from_list = False
        translated = _INFO_TABLES.sub(
            "(SELECT name AS table_name FROM sqlite_master WHERE type='table')",
            translated,
        )
        conn._begin_if_needed()
        try:
            self._cur.execute(translated, tuple(params))
        except sqlite3.Error as e:
            raise ProgrammingError(str(e)) from e
        self.rowcount = self._cur.rowcount
        return self

    def fetchone(self):
        if getattr(self, "_from_list", False):
            return self._rows.pop(0) if self._rows else None
        return self._cur.fetchone()

    def fetchall(self):
        if getattr(self, "_from_list", False):
            rows, self._rows = self._rows, []
            return rows
        return self._cur.fetchall()

    def __iter__(self):
        if getattr(self, "_from_list", False):
            rows, self._rows = self._rows, []
            return iter(rows)
        return iter(self._cur)

    def close(self) -> None:
        self._cur.close()
