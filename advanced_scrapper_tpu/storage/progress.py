"""Progress ledger: processed/failed sets persisted after every item.

Successor of ``progress.json``
(``ticker_symbol_query_rate_limit_protected.py:340-353,410-415``) including
the "marked done but artifact missing" repair check (:381-393).
"""

from __future__ import annotations

import json
import os
from typing import Callable


class ProgressLedger:
    def __init__(self, path: str):
        self.path = path
        self.processed: set[str] = set()
        self.failed: set[str] = set()
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
            self.processed = set(data.get("processed", []))
            self.failed = set(data.get("failed", []))

    def save(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {"processed": sorted(self.processed), "failed": sorted(self.failed)},
                f,
            )
        os.replace(tmp, self.path)

    def mark_processed(self, key: str) -> None:
        self.processed.add(key)
        self.failed.discard(key)
        self.save()

    def mark_failed(self, key: str) -> None:
        self.failed.add(key)
        self.save()

    def should_skip(self, key: str, artifact_exists: Callable[[], bool]) -> bool:
        """Skip keys already processed — unless their artifact vanished, in
        which case they are un-marked for re-processing (repair semantics,
        ref :381-393)."""
        if key not in self.processed:
            return False
        if artifact_exists():
            return True
        self.processed.discard(key)
        self.save()
        return False
