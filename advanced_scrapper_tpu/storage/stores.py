"""SQLite link/article stores with DB-flag resume.

Re-implements the reference's live-poller persistence
(``experiental/09_btc_links.py:15-27``, ``10_btc_articles.py:48-112``):

- ``links(url PRIMARY KEY, first_seen_utc, first_seen_unix,
  is_scraped DEFAULT 0)`` — insert-or-ignore discovery; the ``is_scraped``
  flag is the resume checkpoint (SURVEY.md §5.4 flavor 4);
- ``articles(url PRIMARY KEY, title, author, datetime_utc, datetime_unix,
  content, ticker_symbols)`` — upsert + flag flip in one transaction.

A Postgres twin of the link store exists in the reference
(``04_crypto_1.py:14-34``, ``INSERT … ON CONFLICT DO NOTHING``); psycopg2
is not available in this environment, so :class:`LinkStore` exposes the same
interface over SQLite and a Postgres URL raises a clear error.
"""

from __future__ import annotations

import json
import sqlite3
import time
from datetime import datetime, timezone

from dateutil import parser as dateparser


class LinkStore:
    """links table: discovery + is_scraped checkpoint."""

    def __init__(self, db_path: str):
        if db_path.startswith(("postgres://", "postgresql://")):
            raise RuntimeError(
                "Postgres link store requires psycopg2, which is not "
                "installed; use a sqlite path"
            )
        self.db_path = db_path
        with self._conn() as conn:
            conn.execute(
                """
                CREATE TABLE IF NOT EXISTS links (
                    url TEXT PRIMARY KEY,
                    first_seen_utc TIMESTAMP,
                    first_seen_unix INTEGER,
                    is_scraped INTEGER DEFAULT 0
                )
                """
            )

    def _conn(self) -> sqlite3.Connection:
        return sqlite3.connect(self.db_path)

    def add_links(self, urls: list[str], now: float | None = None) -> int:
        """Insert-or-ignore; returns the number of NEW links."""
        ts = now if now is not None else time.time()
        utc = datetime.fromtimestamp(ts, timezone.utc).strftime("%Y-%m-%d %H:%M:%S")
        new = 0
        with self._conn() as conn:
            for u in urls:
                cur = conn.execute(
                    "INSERT OR IGNORE INTO links (url, first_seen_utc, first_seen_unix)"
                    " VALUES (?, ?, ?)",
                    (u, utc, int(ts)),
                )
                new += cur.rowcount
        return new

    def unscraped(self) -> list[str]:
        with self._conn() as conn:
            rows = conn.execute("SELECT url FROM links WHERE is_scraped = 0").fetchall()
        return [r[0] for r in rows]

    def mark_scraped(self, url: str) -> None:
        with self._conn() as conn:
            conn.execute("UPDATE links SET is_scraped = 1 WHERE url = ?", (url,))

    def counts(self) -> tuple[int, int]:
        with self._conn() as conn:
            total = conn.execute("SELECT COUNT(*) FROM links").fetchone()[0]
            done = conn.execute(
                "SELECT COUNT(*) FROM links WHERE is_scraped = 1"
            ).fetchone()[0]
        return total, done


class ArticleStore:
    """articles table: extractor-record upsert + link flag flip."""

    def __init__(self, db_path: str):
        self.db_path = db_path
        with self._conn() as conn:
            conn.execute(
                """
                CREATE TABLE IF NOT EXISTS articles (
                    url TEXT PRIMARY KEY,
                    title TEXT,
                    author TEXT,
                    datetime_utc TIMESTAMP,
                    datetime_unix INTEGER,
                    content TEXT,
                    ticker_symbols TEXT
                )
                """
            )

    def _conn(self) -> sqlite3.Connection:
        return sqlite3.connect(self.db_path)

    def store(self, url: str, data: dict) -> None:
        """Upsert one extracted record and flip the link flag (ref 10:81-112)."""
        raw_dt = data.get("datetime") or None
        dt_utc = dt_unix = None
        if raw_dt:
            try:
                parsed = dateparser.parse(str(raw_dt))
                dt_utc = parsed.strftime("%Y-%m-%d %H:%M:%S")
                dt_unix = int(parsed.timestamp())
            except (ValueError, OverflowError):
                pass
        with self._conn() as conn:
            conn.execute(
                "INSERT OR REPLACE INTO articles "
                "(url, title, author, content, datetime_utc, datetime_unix, ticker_symbols)"
                " VALUES (?, ?, ?, ?, ?, ?, ?)",
                (
                    url,
                    str(data.get("title")) if data.get("title") is not None else None,
                    str(data.get("author")) if data.get("author") is not None else None,
                    str(data.get("article")) if data.get("article") is not None else None,
                    dt_utc,
                    dt_unix,
                    json.dumps(data.get("ticker_symbols"))
                    if data.get("ticker_symbols") is not None
                    else None,
                ),
            )
            # flip the link flag only when this DB also hosts a links table
            # (the reference shares one file; independent files are legal here
            # and must not roll back the article insert)
            has_links = conn.execute(
                "SELECT 1 FROM sqlite_master WHERE type='table' AND name='links'"
            ).fetchone()
            if has_links:
                conn.execute("UPDATE links SET is_scraped = 1 WHERE url = ?", (url,))

    def all_texts(self):
        """Yield (url, content) pairs — the cross-source dedup feed.

        Lazy: rows stream off the sqlite cursor so a multi-GB store never
        materialises on the host at once.
        """
        with self._conn() as conn:
            for r in conn.execute("SELECT url, COALESCE(content, '') FROM articles"):
                yield (r[0], r[1])

    def count(self) -> int:
        with self._conn() as conn:
            return conn.execute("SELECT COUNT(*) FROM articles").fetchone()[0]
