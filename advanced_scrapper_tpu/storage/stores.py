"""Link/article stores with DB-flag resume, over a pluggable DB backend.

Re-implements the reference's live-poller persistence across BOTH of its
database stacks (``storage/backends.py``):

- SQLite (``experiental/09_btc_links.py:15-27``, ``10_btc_articles.py:48-112``)
  — the default;
- Postgres (``04_crypto_1.py:14-34``: ``CREATE DATABASE`` bootstrap,
  ``INSERT … ON CONFLICT DO NOTHING``) — same store code over a DBAPI
  driver.

Schema:

- ``links(url PRIMARY KEY, first_seen_utc, first_seen_unix,
  is_scraped DEFAULT 0)`` — insert-or-ignore discovery; the ``is_scraped``
  flag is the resume checkpoint (SURVEY.md §5.4 flavor 4);
- ``articles(url PRIMARY KEY, title, author, datetime_utc, datetime_unix,
  content, ticker_symbols)`` — upsert + flag flip in one transaction.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager
from datetime import datetime, timezone

from dateutil import parser as dateparser

from advanced_scrapper_tpu.storage.backends import make_backend

_LINK_COLS = ["url", "first_seen_utc", "first_seen_unix"]
_ARTICLE_COLS = [
    "url", "title", "author", "content",
    "datetime_utc", "datetime_unix", "ticker_symbols",
]


class _StoreBase:
    def __init__(self, target, *, driver=None):
        # target: sqlite path, postgres DSN, or a prebuilt backend object
        if isinstance(target, str):
            self.backend = make_backend(target, driver=driver)
        else:
            self.backend = target
        self.db_path = getattr(self.backend, "path", getattr(self.backend, "dsn", ""))

    @contextmanager
    def _conn(self):
        conn = self.backend.connect()
        try:
            with conn:  # one transaction per store operation (both DBAPIs)
                yield conn
        finally:
            conn.close()


class LinkStore(_StoreBase):
    """links table: discovery + is_scraped checkpoint."""

    def __init__(self, target, *, driver=None):
        super().__init__(target, driver=driver)
        with self._conn() as conn:
            conn.cursor().execute(
                """
                CREATE TABLE IF NOT EXISTS links (
                    url TEXT PRIMARY KEY,
                    first_seen_utc TIMESTAMP,
                    first_seen_unix INTEGER,
                    is_scraped INTEGER DEFAULT 0
                )
                """
            )

    def add_links(self, urls: list[str], now: float | None = None) -> list[str]:
        """Insert-or-ignore; returns the urls that were NEW (in input order).

        The reference's Postgres poller relies on exactly this
        insert-or-ignore semantics (``04_crypto_1.py:76-80``)."""
        ts = now if now is not None else time.time()
        utc = datetime.fromtimestamp(ts, timezone.utc).strftime("%Y-%m-%d %H:%M:%S")
        sql = self.backend.insert_ignore_sql("links", _LINK_COLS, "url")
        new: list[str] = []
        with self._conn() as conn:
            cur = conn.cursor()
            for u in urls:
                cur.execute(sql, (u, utc, int(ts)))
                if cur.rowcount:
                    new.append(u)
        return new

    def unscraped(self) -> list[str]:
        with self._conn() as conn:
            cur = conn.cursor()
            cur.execute("SELECT url FROM links WHERE is_scraped = 0")
            return [r[0] for r in cur.fetchall()]

    def mark_scraped(self, url: str) -> None:
        p = self.backend.paramstyle
        with self._conn() as conn:
            conn.cursor().execute(
                f"UPDATE links SET is_scraped = 1 WHERE url = {p}", (url,)
            )

    def counts(self) -> tuple[int, int]:
        with self._conn() as conn:
            cur = conn.cursor()
            cur.execute("SELECT COUNT(*) FROM links")
            total = cur.fetchone()[0]
            cur.execute("SELECT COUNT(*) FROM links WHERE is_scraped = 1")
            done = cur.fetchone()[0]
        return total, done


class ArticleStore(_StoreBase):
    """articles table: extractor-record upsert + link flag flip."""

    def __init__(self, target, *, driver=None):
        super().__init__(target, driver=driver)
        with self._conn() as conn:
            conn.cursor().execute(
                """
                CREATE TABLE IF NOT EXISTS articles (
                    url TEXT PRIMARY KEY,
                    title TEXT,
                    author TEXT,
                    datetime_utc TIMESTAMP,
                    datetime_unix INTEGER,
                    content TEXT,
                    ticker_symbols TEXT
                )
                """
            )

    def store(self, url: str, data: dict) -> None:
        """Upsert one extracted record and flip the link flag (ref 10:81-112)."""
        raw_dt = data.get("datetime") or None
        dt_utc = dt_unix = None
        if raw_dt:
            try:
                parsed = dateparser.parse(str(raw_dt))
                dt_utc = parsed.strftime("%Y-%m-%d %H:%M:%S")
                dt_unix = int(parsed.timestamp())
            except (ValueError, OverflowError):
                pass
        sql = self.backend.upsert_sql("articles", _ARTICLE_COLS, "url")
        with self._conn() as conn:
            cur = conn.cursor()
            cur.execute(
                sql,
                (
                    url,
                    str(data.get("title")) if data.get("title") is not None else None,
                    str(data.get("author")) if data.get("author") is not None else None,
                    str(data.get("article")) if data.get("article") is not None else None,
                    dt_utc,
                    dt_unix,
                    json.dumps(data.get("ticker_symbols"))
                    if data.get("ticker_symbols") is not None
                    else None,
                ),
            )
            # flip the link flag only when this DB also hosts a links table
            # (the reference shares one file; independent files are legal here
            # and must not roll back the article insert)
            if self.backend.has_table(conn, "links"):
                p = self.backend.paramstyle
                cur.execute(
                    f"UPDATE links SET is_scraped = 1 WHERE url = {p}", (url,)
                )

    def all_texts(self):
        """Yield (url, content) pairs — the cross-source dedup feed.

        Lazy: rows stream off the cursor so a multi-GB store never
        materialises on the host at once.
        """
        with self._conn() as conn:
            cur = conn.cursor()
            cur.execute("SELECT url, COALESCE(content, '') FROM articles")
            for r in cur:
                yield (r[0], r[1])

    def count(self) -> int:
        with self._conn() as conn:
            cur = conn.cursor()
            cur.execute("SELECT COUNT(*) FROM articles")
            return cur.fetchone()[0]
