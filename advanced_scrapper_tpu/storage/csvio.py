"""Resumable append-only CSV stores, torn-tail safe.

Output-artifact-as-checkpoint is the reference's resilience model
(SURVEY.md §5.4): success/failed CSVs are re-read on startup and the work
list anti-joined (``constant_rate_scrapper.py:316-356``); every row is
flushed immediately so the checkpoint is always current (:448,:458).
:class:`AppendCsv` packages that idiom: append mode, header-if-empty,
flush-per-row, and a lock so it is safe even if a caller shares it across
threads (the engine itself keeps a single writer thread by construction).

Crash-anywhere contract (this PR's durability layer): a process killed
mid-``write_row`` leaves a *torn tail* — a final partial record.  Every
writer, and every reader of FRAMEWORK-OWNED append artifacts (the resume
anti-join via :func:`scraped_url_set`, :func:`count_rows`), runs
:func:`repair_torn_tail` first, which moves the torn bytes to a
``<path>.quarantine`` sidecar and truncates the file back to its last
complete record.  (Externally-authored work lists are read leniently and
never mutated — a hand-made CSV may legitimately end without a trailing
newline; see :func:`read_url_column`.)  Three invariants follow:

- **no crash**: the anti-join never feeds partial bytes to a parser;
- **no silent parse**: a torn row can never masquerade as a completed URL
  (it is quarantined, so its URL stays eligible for resume);
- **no duplication**: re-scraping the torn URL appends a fresh row to a
  file that no longer contains the torn one, and appends never
  concatenate onto a dangling partial record.

All I/O goes through the ``storage.fsio`` seam so the chaos backend can
inject short writes / EIO / crash-mid-write underneath these guarantees
(``tests/test_chaos_storage.py``, ``tools/crashsweep.py``).
"""

from __future__ import annotations

import csv
import io
import os
import threading
from typing import Sequence

from advanced_scrapper_tpu.storage.fsio import default_fs

_CHUNK = 1 << 20


def _clean_end(fh) -> int:
    """Byte offset just past the last COMPLETE record of an open binary CSV.

    A newline terminates a record iff the number of quote characters before
    it is even (inside a quoted field the running count is odd — embedded
    newlines and doubled escape quotes both preserve this, per the csv
    quoting grammar).  One forward chunked pass: splitting a chunk on the
    quote character yields segments whose parity alternates from the
    running parity, so the last even-parity newline per chunk falls out of
    C-speed ``split``/``rfind`` — multi-GB resume files are validated in a
    single read."""
    fh.seek(0)
    parity = 0  # quote count so far, mod 2
    pos = 0     # absolute offset of the current chunk
    last = 0    # offset just past the newest even-parity newline
    while True:
        chunk = fh.read(_CHUNK)
        if not chunk:
            return last
        parts = chunk.split(b'"')
        off = 0  # offset of parts[i] within the chunk
        best = -1
        for i, part in enumerate(parts):
            if (parity + i) % 2 == 0:
                k = part.rfind(b"\n")
                if k >= 0:
                    best = off + k
            off += len(part) + 1  # +1 for the quote that ended this part
        if best >= 0:
            last = pos + best + 1
        parity = (parity + len(parts) - 1) % 2
        pos += len(chunk)


#: (ino, size, mtime_ns) of files verified clean — a restart touches the
#: same resume CSV several times in a row (anti-join read, then the
#: AppendCsv reopen moments later); re-scanning a multi-GB file that
#: nothing wrote in between is pure re-work.  Any write moves size/mtime
#: and misses the cache, so a genuinely torn tail is always re-scanned.
_clean_cache: dict[str, tuple[int, int, int]] = {}


def _stat_sig(path: str) -> tuple[int, int, int] | None:
    try:
        st = os.stat(path)
        return (st.st_ino, st.st_size, st.st_mtime_ns)
    except OSError:
        return None


def repair_torn_tail(path: str, fs=None) -> int:
    """Quarantine a torn final record: move the bytes past the last complete
    record to ``<path>.quarantine`` and truncate the file back to whole
    records.  Returns the number of torn bytes moved (0 = file was clean).

    Quarantine-then-truncate on purpose: a crash between the two steps
    leaves the torn bytes in both places and the next repair simply
    quarantines them again — duplicate quarantine entries are harmless,
    silently deleted evidence is not.
    """
    fs = fs or default_fs()
    if not fs.exists(path):
        return 0
    key = os.path.abspath(path)
    sig = _stat_sig(path)
    if sig is not None and _clean_cache.get(key) == sig:
        return 0  # verified clean at this exact (ino, size, mtime)
    with fs.open(path, "rb") as fh:
        good = _clean_end(fh)
        fh.seek(0, os.SEEK_END)
        size = fh.tell()
        if good >= size:
            if sig is not None:
                _clean_cache[key] = sig
            return 0
        fh.seek(good)
        torn = fh.read(size - good)
    with fs.open(path + ".quarantine", "ab") as q:
        q.write(torn if torn.endswith(b"\n") else torn + b"\n")
        q.flush()
        try:
            fs.fsync(q)
        except OSError:
            pass
    with fs.open(path, "r+b") as fh:
        fh.truncate(good)
        fh.flush()
        try:
            fs.fsync(fh)
        except OSError:
            pass
    repaired = _stat_sig(path)
    if repaired is not None:
        _clean_cache[os.path.abspath(path)] = repaired
    # a torn tail IS a detected crash artifact — count it and put it on
    # the flight recorder so restarts show their repair work on /metrics
    from advanced_scrapper_tpu.obs import telemetry, trace

    telemetry.event_counter(
        "astpu_quarantine_total",
        "crash artifacts quarantined, by kind",
        kind="csv_torn_tail",
    ).inc()
    telemetry.event_counter(
        "astpu_quarantine_bytes_total",
        "bytes moved to quarantine sidecars",
        kind="csv_torn_tail",
    ).inc(len(torn))
    trace.record(
        "event", "quarantine.csv_torn_tail", path=os.path.basename(path),
        bytes=len(torn),
    )
    return len(torn)


class AppendCsv:
    def __init__(self, path: str, fieldnames: Sequence[str], fs=None):
        self.path = path
        self.fieldnames = list(fieldnames)
        self._fs = fs or default_fs()
        self._lock = threading.Lock()
        # append-after-torn-tail would concatenate the new row onto the
        # partial one, corrupting BOTH — repair before the append handle
        # ever opens
        repair_torn_tail(path, fs=self._fs)
        existed = self._fs.exists(path) and self._fs.size(path) > 0
        self._fh = self._fs.open(path, "a", newline="", encoding="utf-8")
        self._writer = csv.DictWriter(self._fh, fieldnames=self.fieldnames)
        if not existed:
            self._writer.writeheader()
            self._fh.flush()

    def write_row(self, data: dict) -> None:
        """Write one row (missing fields become ''), flushing immediately."""
        row = {f: data.get(f, "") for f in self.fieldnames}
        with self._lock:
            self._writer.writerow(row)
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            self._fh.close()

    def __enter__(self) -> "AppendCsv":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _BoundedRaw(io.RawIOBase):
    """Read-only raw view of the first ``limit`` bytes of an open binary
    file — lets the degraded-substrate fallback stream a multi-GB clean
    region through the csv parser instead of buffering it whole."""

    def __init__(self, fh, limit: int):
        self._fh = fh
        self._left = limit

    def readable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        if self._left <= 0:
            return 0
        n = self._fh.readinto(memoryview(b)[: min(len(b), self._left)])
        self._left -= n
        return n

    def close(self) -> None:
        try:
            self._fh.close()
        finally:
            super().close()


def _open_clean_text(path: str, fs):
    """Text handle over the file's whole-record region, read without
    mutating it (the fallback for substrates where the repair write is
    not permitted).  Caller closes it (closes the chain)."""
    fh = fs.open(path, "rb")
    try:
        good = _clean_end(fh)
        fh.seek(0)
    except BaseException:
        fh.close()
        raise
    return io.TextIOWrapper(
        io.BufferedReader(_BoundedRaw(fh, good)),
        encoding="utf-8",
        errors="replace",
        newline="",
    )


def _read_clean_region(path: str, column: str, fs) -> list[str]:
    out: list[str] = []
    with _open_clean_text(path, fs) as txt:
        for row in csv.DictReader(txt):
            v = row.get(column)
            if v is not None:
                out.append(str(v))
    return out


def read_url_column(
    path: str, column: str = "url", fs=None, repair: bool = False
) -> list[str]:
    """Read one column as strings.

    Served by the C++ scanner (``native/csvscan.cpp``) when available —
    the resume anti-join re-reads multi-GB article CSVs on every start,
    the same job the reference hands to pandas' C parser
    (``constant_rate_scrapper.py:316-356``) — with a byte-equal Python
    ``csv`` fallback (equivalence is golden- and fuzz-tested).

    ``repair=True`` quarantines a torn tail first; it is only correct for
    FRAMEWORK-OWNED append artifacts (success/failed/annotation CSVs),
    whose writer newline-terminates every record — there, an unterminated
    tail IS a torn write.  The default read is lenient and non-mutating:
    an externally-authored work list may legitimately end without a
    trailing newline, and its final row must neither be dropped nor the
    user's file rewritten.  (:func:`scraped_url_set` — the resume
    anti-join over framework-owned CSVs — repairs by default.)
    """
    fs = fs or default_fs()
    if not fs.exists(path):
        return []
    if repair:
        try:
            repair_torn_tail(path, fs=fs)
        except OSError:
            return _read_clean_region(path, column, fs)
    from advanced_scrapper_tpu.cpu.csvnative import scan_column

    native = scan_column(path, column)
    if native is not None:
        return native
    out: list[str] = []
    with fs.open(path, newline="", encoding="utf-8") as fh:
        for row in csv.DictReader(fh):
            v = row.get(column)
            if v is not None:
                out.append(str(v))
    return out


def scraped_url_set(
    *paths: str, column: str = "url", fs=None, repair: bool = True
) -> set[str]:
    """Union of url columns across existing CSVs — the resume anti-join set
    (``constant_rate_scrapper.py:317-342``).  These are the framework's
    own success/failed CSVs, so torn tails are quarantined first: a torn
    row must never masquerade as a completed URL."""
    seen: set[str] = set()
    for p in paths:
        seen.update(read_url_column(p, column, fs=fs, repair=repair))
    return seen


def count_rows(path: str, fs=None, repair: bool = True) -> int:
    """Data-row count of a framework-owned CSV (repairs torn tails first,
    like :func:`scraped_url_set`; pass ``repair=False`` for files the
    framework does not write)."""
    fs = fs or default_fs()
    if not fs.exists(path):
        return 0
    if repair:
        try:
            repair_torn_tail(path, fs=fs)
        except OSError:
            with _open_clean_text(path, fs) as txt:
                n = sum(1 for _ in csv.reader(txt))
            return max(0, n - 1)
    with fs.open(path, newline="", encoding="utf-8") as fh:
        n = sum(1 for _ in csv.reader(fh))
    return max(0, n - 1)  # minus header
