"""Resumable append-only CSV stores.

Output-artifact-as-checkpoint is the reference's resilience model
(SURVEY.md §5.4): success/failed CSVs are re-read on startup and the work
list anti-joined (``constant_rate_scrapper.py:316-356``); every row is
flushed immediately so the checkpoint is always current (:448,:458).
:class:`AppendCsv` packages that idiom: append mode, header-if-empty,
flush-per-row, and a lock so it is safe even if a caller shares it across
threads (the engine itself keeps a single writer thread by construction).
"""

from __future__ import annotations

import csv
import os
import threading
from typing import Iterable, Sequence


class AppendCsv:
    def __init__(self, path: str, fieldnames: Sequence[str]):
        self.path = path
        self.fieldnames = list(fieldnames)
        self._lock = threading.Lock()
        existed = os.path.exists(path) and os.stat(path).st_size > 0
        self._fh = open(path, "a", newline="", encoding="utf-8")
        self._writer = csv.DictWriter(self._fh, fieldnames=self.fieldnames)
        if not existed:
            self._writer.writeheader()
            self._fh.flush()

    def write_row(self, data: dict) -> None:
        """Write one row (missing fields become ''), flushing immediately."""
        row = {f: data.get(f, "") for f in self.fieldnames}
        with self._lock:
            self._writer.writerow(row)
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            self._fh.close()

    def __enter__(self) -> "AppendCsv":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_url_column(path: str, column: str = "url") -> list[str]:
    """Read one column as strings.

    Served by the C++ scanner (``native/csvscan.cpp``) when available —
    the resume anti-join re-reads multi-GB article CSVs on every start,
    the same job the reference hands to pandas' C parser
    (``constant_rate_scrapper.py:316-356``) — with a byte-equal Python
    ``csv`` fallback (equivalence is golden- and fuzz-tested).
    """
    if not os.path.exists(path):
        return []
    from advanced_scrapper_tpu.cpu.csvnative import scan_column

    native = scan_column(path, column)
    if native is not None:
        return native
    out: list[str] = []
    with open(path, newline="", encoding="utf-8") as fh:
        for row in csv.DictReader(fh):
            v = row.get(column)
            if v is not None:
                out.append(str(v))
    return out


def scraped_url_set(*paths: str, column: str = "url") -> set[str]:
    """Union of url columns across existing CSVs — the resume anti-join set
    (``constant_rate_scrapper.py:317-342``)."""
    seen: set[str] = set()
    for p in paths:
        seen.update(read_url_column(p, column))
    return seen


def count_rows(path: str) -> int:
    if not os.path.exists(path):
        return 0
    with open(path, newline="", encoding="utf-8") as fh:
        n = sum(1 for _ in csv.reader(fh))
    return max(0, n - 1)  # minus header
