from advanced_scrapper_tpu.storage.csvio import (
    AppendCsv,
    read_url_column,
    scraped_url_set,
)
from advanced_scrapper_tpu.storage.progress import ProgressLedger

__all__ = ["AppendCsv", "read_url_column", "scraped_url_set", "ProgressLedger"]
