"""DB backend seam for the link/article stores.

The reference runs TWO database stacks side by side: Postgres for the live
crypto pollers (``experiental/04_crypto_1.py:14-34`` — ``CREATE DATABASE``
bootstrap, ``INSERT … ON CONFLICT DO NOTHING``) and SQLite for the BTC
poller (``09_btc_links.py:15-27``).  Round 1 collapsed both onto sqlite
with no way back; this seam restores the dual-store reality:

- :class:`SqliteBackend` — stdlib, the default.
- :class:`PostgresBackend` — same store code over a DBAPI driver
  (psycopg2 when installed; any compatible module can be injected, which
  is also how the seam is tested in an environment without Postgres).

The stores speak a small dialect surface (paramstyle, insert-or-ignore,
upsert, has_table) rather than hardcoding SQL strings per engine — both
engines support the modern ``ON CONFLICT`` form, so the differences are
genuinely small.
"""

from __future__ import annotations

import sqlite3


class SqliteBackend:
    """Default backend: one sqlite file (or ':memory:')."""

    paramstyle = "?"

    def __init__(self, path: str):
        self.path = path

    def connect(self):
        return sqlite3.connect(self.path)

    def insert_ignore_sql(self, table: str, cols: list[str], conflict_col: str) -> str:
        ph = ", ".join([self.paramstyle] * len(cols))
        return (
            f"INSERT INTO {table} ({', '.join(cols)}) VALUES ({ph}) "
            f"ON CONFLICT ({conflict_col}) DO NOTHING"
        )

    def upsert_sql(self, table: str, cols: list[str], conflict_col: str) -> str:
        ph = ", ".join([self.paramstyle] * len(cols))
        updates = ", ".join(
            f"{c} = excluded.{c}" for c in cols if c != conflict_col
        )
        return (
            f"INSERT INTO {table} ({', '.join(cols)}) VALUES ({ph}) "
            f"ON CONFLICT ({conflict_col}) DO UPDATE SET {updates}"
        )

    def has_table(self, conn, name: str) -> bool:
        cur = conn.execute(
            "SELECT 1 FROM sqlite_master WHERE type='table' AND name=?", (name,)
        )
        return cur.fetchone() is not None


class PostgresBackend:
    """Postgres over a DBAPI driver (psycopg2-compatible).

    ``driver`` may be injected (tests, alternative drivers); by default
    psycopg2 is imported lazily and a missing install raises with a clear
    message — matching the reference's hard psycopg2 dependency
    (``04_crypto_1.py:6``).
    """

    paramstyle = "%s"

    def __init__(self, dsn: str, driver=None):
        if driver is None:
            try:
                import psycopg2 as driver  # type: ignore[no-redef]
            except ImportError as e:
                raise RuntimeError(
                    "Postgres store requires psycopg2 (not installed); "
                    "install it, inject a DBAPI driver, or use a sqlite path"
                ) from e
        self.driver = driver
        self.dsn = dsn

    def connect(self):
        return self.driver.connect(self.dsn)

    def ensure_database(self, name: str, admin_dsn: str) -> None:
        """``CREATE DATABASE`` bootstrap (ref 04_crypto_1.py:14-34): connect
        to an admin database, create ``name`` if absent."""
        conn = self.driver.connect(admin_dsn)
        try:
            conn.autocommit = True  # CREATE DATABASE cannot run in a txn
            cur = conn.cursor()
            cur.execute("SELECT 1 FROM pg_database WHERE datname = %s", (name,))
            if cur.fetchone() is None:
                cur.execute(f'CREATE DATABASE "{name}"')
        finally:
            conn.close()

    def insert_ignore_sql(self, table: str, cols: list[str], conflict_col: str) -> str:
        ph = ", ".join([self.paramstyle] * len(cols))
        return (
            f"INSERT INTO {table} ({', '.join(cols)}) VALUES ({ph}) "
            f"ON CONFLICT ({conflict_col}) DO NOTHING"
        )

    def upsert_sql(self, table: str, cols: list[str], conflict_col: str) -> str:
        ph = ", ".join([self.paramstyle] * len(cols))
        updates = ", ".join(
            f"{c} = excluded.{c}" for c in cols if c != conflict_col
        )
        return (
            f"INSERT INTO {table} ({', '.join(cols)}) VALUES ({ph}) "
            f"ON CONFLICT ({conflict_col}) DO UPDATE SET {updates}"
        )

    def has_table(self, conn, name: str) -> bool:
        cur = conn.cursor()
        cur.execute(
            "SELECT 1 FROM information_schema.tables WHERE table_name = %s",
            (name,),
        )
        return cur.fetchone() is not None


def make_backend(target: str, *, driver=None):
    """``postgres://``/``postgresql://`` DSN → Postgres; anything else is a
    sqlite path."""
    if target.startswith(("postgres://", "postgresql://")):
        return PostgresBackend(target, driver=driver)
    return SqliteBackend(target)
