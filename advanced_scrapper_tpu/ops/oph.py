"""One-permutation MinHash (OPH) — the high-throughput signature backend.

The dense kernel (``ops/minhash.py``) applies all ``num_perm`` permutations
to every shingle hash: O(S × P) integer multiply-adds per document — the
textbook formulation, kept as the datasketch-parity default.  OPH (Li,
Owen & Zhang, "One Permutation Hashing", NeurIPS 2012) computes **one**
hash per shingle, partitions the 32-bit hash space into ``num_perm`` bins
(the bin is simply the top ``log2(num_perm)`` bits), and takes the minimum
hash per bin — O(S) hashing plus one sort.  Empty bins are filled by
rotation densification (Shrivastava & Li, ICML 2014), which preserves the
unbiasedness of the collision estimate.  The recall-vs-oracle test holds
≥0.95 on the same corpus as the dense path (``tests/test_oph.py``).

**Measured slower on v5e (2026-07): ~16× under the dense scan** — the
[B, S] row sort is data movement the TPU pays dearly for, while XLA fuses
the dense kernel's multiply-adds into the min-reduction at near-VPU rates.
OPH's O(S) vs O(S·P) asymptotic advantage does not survive the hardware:
regular arithmetic beats sorting here.  Kept as an opt-in backend
(``DedupConfig.backend="oph"``) — the estimator-quality tests and the
min-combine algebra are useful, and the trade may flip on future
hardware or for ``num_perm`` ≫ 128.

Sort-based bin minima are XLA-idiomatic: because the bin id is the hash's
top bits, one ascending ``lax.sort`` of the row groups bins *and* orders
each bin's members — the per-bin minimum is the element at each bin's
lower-bound ``searchsorted`` position.  No scatters.

Composition rule: **raw** signatures (empty bins = ``U32_MAX``) combine
exactly under elementwise minimum — the same algebra the blockwise split
(``ops.minhash.combine_block_signatures``) and the sequence-parallel
``lax.pmin`` rely on.  Densification must happen *after* all mins are
combined (``min(densify(a), densify(b)) != densify(min(a, b))`` — a
borrowed value can mask a real bin minimum from the other operand), which
is why the raw and densified forms are separate functions.

Reference lineage: this accelerates the same capability as the reference's
single-core pandas exact dedup + rapidfuzz near-matching
(``yahoo_links_selenium.py:174``, ``match_keywords.py:174-180``) per the
north star in BASELINE.json.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from advanced_scrapper_tpu.core.hashing import MinHashParams
from advanced_scrapper_tpu.ops.shingle import U32_MAX, shingle_hash


def _bin_bits(num_perm: int) -> int:
    bits = num_perm.bit_length() - 1
    if 1 << bits != num_perm:
        raise ValueError(f"OPH requires power-of-two num_perm, got {num_perm}")
    return bits


@partial(jax.jit, static_argnames=("k", "num_perm"))
def _raw_impl(tokens, lengths, *, k: int, num_perm: int):
    bits = _bin_bits(num_perm)
    shift = jnp.uint32(32 - bits)
    h, valid = shingle_hash(tokens, lengths, k)      # uint32[B, S]
    h = jnp.where(valid, h, U32_MAX)
    hs = jax.lax.sort(h, dimension=1)                # bin == top bits ⇒ grouped
    B, S = hs.shape
    bins = jnp.arange(num_perm, dtype=jnp.uint32)
    bounds = bins << shift
    pos = jax.vmap(lambda row: jnp.searchsorted(row, bounds, side="left"))(hs)
    v = jnp.take_along_axis(hs, jnp.clip(pos, 0, S - 1), axis=1)  # [B, P]
    inbin = (v >> shift) == bins[None, :]
    return jnp.where(inbin & (pos < S), v, U32_MAX)


def oph_raw_signatures(tokens, lengths, params: MinHashParams):
    """``uint32[B, num_perm]`` per-bin minima; empty bins are ``U32_MAX``.

    Raw signatures combine exactly under elementwise ``min`` (blockwise
    split, sequence-parallel ``pmin``); densify *after* combining.
    """
    return _raw_impl(
        tokens, lengths, k=params.shingle_k, num_perm=params.num_perm
    )


_DENSIFY_C = jnp.uint32(0x9E3779B1)  # odd ⇒ bijective mix per hop distance


@jax.jit
def densify(sig):
    """Rotation densification with distance offsetting (Shrivastava & Li,
    ICML 2014): each empty bin borrows the nearest filled bin to its right
    (circular), and the borrowed value is offset by ``distance × C`` so two
    documents' jointly-empty bins only agree when they borrowed the *same*
    value from the *same relative position* — without the offset, one
    shared shingle replicates across both documents' empty runs and
    inflates signature agreement for sparse (short) documents.  All-empty
    rows stay all-``U32_MAX`` (the "no shingles" sentinel contract)."""
    P = sig.shape[-1]
    big = jnp.uint32(0xFFFFFFFF)
    filled = sig != U32_MAX
    dist = jnp.where(filled, jnp.uint32(0), big)
    val = sig
    shift = 1
    while shift < P:
        nd_raw = jnp.roll(dist, -shift, axis=-1)
        nd = jnp.where(nd_raw == big, big, nd_raw + jnp.uint32(shift))
        better = nd < dist
        dist = jnp.where(better, nd, dist)
        val = jnp.where(better, jnp.roll(val, -shift, axis=-1), val)
        shift <<= 1
    dense = val + dist * _DENSIFY_C
    return jnp.where(dist == big, U32_MAX, jnp.where(filled, sig, dense))


def oph_signatures(tokens, lengths, params: MinHashParams):
    """Densified OPH signatures — drop-in for ``minhash_signatures`` on
    whole documents (for block/shard-split documents use the raw form and
    densify after the min-combine)."""
    return densify(oph_raw_signatures(tokens, lengths, params))


