"""Fused Pallas TPU kernel for the MinHash hot loop.

The XLA path (``ops/minhash.py``) expresses shingle-hash → permute → min as a
``lax.scan`` and relies on fusion.  This kernel fuses the whole signature
computation for a batch tile inside VMEM: the k-byte rolling FNV-1a hash, the
128-lane multiply-add permutation family, the validity mask, and the running
per-permutation minimum — one HBM read of the byte tile, one HBM write of the
``uint32[Bt, 128]`` signature tile, nothing materialised in between.

Layout notes (see /opt/skills/guides/pallas_guide.md):
- the permutation axis is exactly 128 — one full VPU lane dimension; the
  running minimum ``[Bt, 128]`` is a stack of native (8, 128) vregs.
- tokens arrive as ``uint8[Bt, L + LANE]`` (callers pad the byte axis by one
  128-lane so every k-window read is in bounds); uint8 VMEM tiles are
  (32, 128), hence the default batch tile of 32 rows.
- the shingle axis is processed in ``chunk``-sized pieces; the peak live
  intermediate is ``uint32[Bt, chunk, 128]`` which the VPU reduces along the
  sublane-tiled middle axis.

This replaces the CPU hot loop the reference runs inside pandas/rapidfuzz
(``yahoo_links_selenium.py:79``, ``match_keywords.py:165-180``) — see
SURVEY.md §6 (north-star 50k articles/s) for why this is the framework's
flagship op.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from advanced_scrapper_tpu.core.hashing import MinHashParams

# Python-int twins of ops.shingle's constants: pallas kernels may not capture
# traced jnp scalars, so the kernel builds its constants from literals.
_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193
_U32_MAX = 0xFFFFFFFF

LANE = 128
_NUM_PERM = 128

#: lazily-resolved "is the backend CPU" probe for the default interpret
#: mode.  Resolved ONCE: the wrapper used to call ``jax.devices()`` on
#: every invocation, which is a per-tile backend query on the legacy
#: (non-fused) dispatch path — and on a tunneled transport a backend
#: query is not free.  The platform cannot change mid-process.
_ON_CPU: bool | None = None


def _on_cpu() -> bool:
    global _ON_CPU
    if _ON_CPU is None:
        _ON_CPU = jax.devices()[0].platform == "cpu"
    return _ON_CPU


def _fmix32(h):
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _minhash_kernel(len_ref, tok_ref, a_ref, b_ref, sig_ref, h_ref, big_ref, *, k, chunk, L):
    """One batch tile: tokens ``uint8[Bt, L+LANE]`` → sig ``uint32[Bt, 128]``."""
    Bt = tok_ref.shape[0]
    tok = tok_ref[:, :].astype(jnp.uint32)  # [Bt, L+LANE]

    # Rolling FNV-1a over the k-byte window at every position 0..L-1.  The
    # window is unrolled (k static, tiny); positions past the text end are
    # killed by the validity mask below.
    h = jnp.full((Bt, L), _FNV_OFFSET, dtype=jnp.uint32)
    for j in range(k):
        h = (h ^ jax.lax.slice(tok, (0, j), (Bt, j + L))) * jnp.uint32(_FNV_PRIME)
    h_ref[:, :] = _fmix32(h)

    lens = len_ref[:, 0]  # int32[Bt]
    n_valid = jnp.maximum(lens - (k - 1), 0)  # shingle count per row
    pos = jax.lax.broadcasted_iota(jnp.int32, (Bt, L), 1)
    # 0/1 validity as int32: Mosaic cannot broadcast an i1 mask into a new
    # minor dim, so the loop body masks arithmetically.
    big_ref[:, :] = (pos < n_valid[:, None]).astype(jnp.int32)

    a = a_ref[0, :]  # uint32[128]
    b = b_ref[0, :]

    # Chunked min-reduction.  Staging h/valid through VMEM scratch lets the
    # loop body slice them dynamically (ref indexing supports dynamic starts
    # where value-level dynamic_slice does not) and bounds live intermediates
    # to one [Bt, chunk, 128] block.  Mosaic lacks unsigned reductions, so
    # minima run sign-flipped (x ^ 0x80000000 maps unsigned order to signed
    # order); the flip is undone on the final store.
    sign = jnp.uint32(0x80000000)
    i32_max = jnp.iinfo(jnp.int32).max

    def body(c, sig):
        off = c * chunk
        hc = h_ref[:, pl.ds(off, chunk)]
        vci = big_ref[:, pl.ds(off, chunk)]  # int32 0/1
        ph = hc[:, :, None] * a[None, None, :] + b[None, None, :]
        phs = jax.lax.bitcast_convert_type(ph ^ sign, jnp.int32)
        # valid → phs, invalid → INT32_MAX (identity of min)
        phs = phs * vci[:, :, None] + ((1 - vci) * i32_max)[:, :, None]
        return jnp.minimum(sig, phs.min(axis=1))

    sig = jnp.full((Bt, _NUM_PERM), i32_max, dtype=jnp.int32)
    sig = jax.lax.fori_loop(0, L // chunk, body, sig)
    sig_ref[:, :] = jax.lax.bitcast_convert_type(sig, jnp.uint32) ^ sign


@partial(jax.jit, static_argnames=("k", "chunk", "block_b", "interpret"))
def _pallas_signatures(
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    a: jnp.ndarray,
    b: jnp.ndarray,
    *,
    k: int,
    chunk: int,
    block_b: int,
    interpret: bool,
) -> jnp.ndarray:
    B, Lp = tokens.shape
    L = Lp - LANE
    grid = (B // block_b,)
    kernel = partial(_minhash_kernel, k=k, chunk=chunk, L=L)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_b, Lp), lambda i: (i, 0)),
            pl.BlockSpec((1, _NUM_PERM), lambda i: (0, 0)),
            pl.BlockSpec((1, _NUM_PERM), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_b, _NUM_PERM), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, _NUM_PERM), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((block_b, L), jnp.uint32),
            pltpu.VMEM((block_b, L), jnp.int32),
        ],
        # renamed across jax releases (TPUCompilerParams → CompilerParams)
        compiler_params=getattr(
            pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
        )(dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(lengths.reshape(B, 1).astype(jnp.int32), tokens, a.reshape(1, -1), b.reshape(1, -1))


def minhash_signatures_pallas(
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    params: MinHashParams,
    *,
    chunk: int = 128,
    block_b: int = 32,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Pallas twin of :func:`ops.minhash.minhash_signatures`.

    Pads the batch up to a ``block_b`` multiple and the byte axis by one
    128-lane (so every k-window read is in bounds), launches the fused
    kernel, and slices the padding back off.  Bit-identical to the XLA path.
    """
    if params.num_perm != _NUM_PERM:
        raise ValueError(f"pallas kernel is specialised to 128 perms, got {params.num_perm}")
    B, L = tokens.shape
    if L % LANE:
        tokens = jnp.pad(tokens, ((0, 0), (0, LANE - L % LANE)))
        L = tokens.shape[1]
    # Largest LANE-multiple divisor of L not exceeding the requested chunk.
    m = L // LANE
    d = min(chunk // LANE, m)
    while m % d:
        d -= 1
    chunk = d * LANE
    pb = -(-B // block_b) * block_b - B
    if pb:
        tokens = jnp.pad(tokens, ((0, pb), (0, 0)))
        lengths = jnp.pad(lengths, ((0, pb),))
    tokens = jnp.pad(tokens, ((0, 0), (0, LANE)))
    if interpret is None:
        interpret = _on_cpu()
    sig = _pallas_signatures(
        tokens,
        lengths,
        jnp.asarray(params.a32),
        jnp.asarray(params.b32),
        k=params.shingle_k,
        chunk=chunk,
        block_b=block_b,
        interpret=interpret,
    )
    return sig[:B] if pb else sig


def pallas_enabled() -> bool:
    """Whether the fused kernel is the preferred signature backend.

    Off by default: on v5e the XLA scan path measures faster (the fused
    kernel pays a lane-broadcast relayout per chunk that XLA's fusion
    avoids; see 2026-07 measurements in the repo docs) — the kernel is kept
    as a measured alternative and a Pallas reference for the op.  Force with
    ``ASTPU_MINHASH_BACKEND=pallas``.
    """
    return os.environ.get("ASTPU_MINHASH_BACKEND", "").lower() == "pallas"
