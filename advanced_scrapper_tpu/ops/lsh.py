"""16-band LSH bucketing and device-side first-seen-wins deduplication.

The reference dedups with pandas ``drop_duplicates(keep='first')``
(``yahoo_links_selenium.py:79,174``) — a hash-table walk on one CPU core.
The TPU formulation turns "same bucket" into a *sort*: for every band,
rows are sorted by (band key, row index); equal-key runs are bucket
collisions, and the run head (smallest row index — i.e. first seen) becomes
every member's candidate representative.  A signature-agreement check
verifies candidates, and log₂(B) rounds of pointer jumping resolve chains so
the final representative array has union-find semantics — all without
leaving the device or introducing data-dependent shapes.

Sorting is the idiomatic XLA substitute for hash tables: ``lax.sort`` is a
fused multi-operand bitonic sort that tiles well on TPU, whereas scattered
hash-table updates would serialise.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from advanced_scrapper_tpu.ops.shingle import FNV_OFFSET, FNV_PRIME, U32_MAX, fmix32


# second-lane constants for the wide (64-bit-entropy) band keys: a distinct
# FNV-style offset/prime pair so the two lanes are independent hashes of the
# same band content (TPUs have no native uint64 — the packing happens on
# host).  numpy (not jnp) scalars: a module-level jnp constant would
# initialise the backend at import time, breaking jax.distributed ordering.
import numpy as _np

_WIDE_OFFSET = _np.uint32(0xCBF29CE4)
_WIDE_PRIME = _np.uint32(0x01000197)


def _fold_bands(sig: jnp.ndarray, nb: int, offset, prime) -> jnp.ndarray:
    """FNV-1a fold of each band's signature rows → uint32[B, nb] (unsalted).

    Single source of the fold used by BOTH :func:`band_keys` and lane 0/1
    of :func:`band_keys_wide`, so their documented equivalence is
    structural, not maintained by parallel editing.
    """
    B, P = sig.shape
    r = P // nb
    rows = sig.reshape(B, nb, r)
    k = jnp.full((B, nb), offset, dtype=jnp.uint32)
    for j in range(r):
        k = (k ^ rows[:, :, j]) * prime
    return k


@jax.jit
def band_keys(sig: jnp.ndarray, band_salt: jnp.ndarray) -> jnp.ndarray:
    """Fold each band's rows into one salted uint32 bucket key.

    ``sig`` is ``uint32[B, num_perm]``; returns ``uint32[B, num_bands]``.
    The north-star config is 16 bands × 8 rows (BASELINE.json).
    """
    nb = band_salt.shape[0]
    k = _fold_bands(sig, nb, FNV_OFFSET, FNV_PRIME)
    return fmix32(k ^ band_salt[None, :])


@jax.jit
def band_keys_wide(sig: jnp.ndarray, band_salt: jnp.ndarray) -> jnp.ndarray:
    """uint32[B, num_bands, 2]: two independent 32-bit keys per band.

    Lane 0 is exactly :func:`band_keys` (same fold, same salt).  Lane 1
    folds the same band rows with different constants and a rotated salt.
    Packed to uint64 on host (``utils.bloom.pack_keys64``) this gives band
    keys whose accidental collision rate is ~n·num_bands/2⁶⁴ — required by
    the unattributed Bloom stream index, where a key collision is an
    unverifiable false drop (32-bit keys lose ~n/2³² of unique docs, ~4%
    at 10M scale).
    """
    nb = band_salt.shape[0]
    lo = _fold_bands(sig, nb, FNV_OFFSET, FNV_PRIME)
    hi = _fold_bands(sig, nb, _WIDE_OFFSET, _WIDE_PRIME)
    salt = band_salt[None, :]
    rot = (salt << jnp.uint32(13)) | (salt >> jnp.uint32(19))
    return jnp.stack([fmix32(lo ^ salt), fmix32(hi ^ rot)], axis=-1)


def _run_head_per_band(kt: jnp.ndarray, idxb: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """For each band row (axis 1 = batch): sorted keys → run-head indices."""
    nb, B = kt.shape
    sk, si = jax.lax.sort((kt, idxb), dimension=1, num_keys=2)
    seg_start = jnp.concatenate(
        [jnp.ones((nb, 1), dtype=bool), sk[:, 1:] != sk[:, :-1]], axis=1
    )
    seg_id = jnp.cumsum(seg_start, axis=1) - 1  # int32 [nb, B], < B
    # si is ascending within each equal-key run, so the run head (first-seen
    # row) is the segment minimum of si.
    run_min = jax.vmap(
        lambda s, g: jax.ops.segment_min(s, g, num_segments=B)
    )(si, seg_id)
    rep_sorted = jnp.take_along_axis(run_min, seg_id, axis=1)
    return si, rep_sorted


@jax.jit
def duplicate_reps(keys: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Candidate representative per row: smallest earlier row sharing any band.

    Args:
      keys: ``uint32[B, num_bands]`` band bucket keys.
      valid: ``bool[B]`` — rows with no shingles (or batch padding) are
        excluded and map to themselves.

    Returns ``int32[B]`` with ``rep[i] <= i``; ``rep[i] == i`` means no
    earlier collision.  Candidates still need signature verification
    (:func:`resolve_reps`) — band collisions can be accidental.
    """
    B, nb = keys.shape
    idx = jnp.arange(B, dtype=jnp.int32)
    keys = jnp.where(valid[:, None], keys, U32_MAX)
    kt = keys.T
    idxb = jnp.broadcast_to(idx, (nb, B))
    si, rep_sorted = _run_head_per_band(kt, idxb)
    rep_band = jax.vmap(
        lambda s, r: jnp.zeros((B,), dtype=jnp.int32).at[s].set(r)
    )(si, rep_sorted)
    rep = rep_band.min(axis=0)
    # Invalid rows were all assigned key U32_MAX and may have grouped with
    # each other; sever them (and protect the pathological valid row that
    # really hashes to U32_MAX) by self-assignment.
    return jnp.where(valid, rep, idx)


@partial(jax.jit, static_argnames=("jump_rounds",))
def resolve_reps(
    rep: jnp.ndarray,
    sig: jnp.ndarray,
    valid: jnp.ndarray,
    threshold: float,
    *,
    jump_rounds: int,
) -> jnp.ndarray:
    """Verify candidates by signature agreement, then resolve chains.

    ``agreement = mean(sig_i == sig_rep)`` is the standard unbiased MinHash
    Jaccard estimate; candidates below ``threshold`` revert to self.
    ``jump_rounds`` should be ≥ ceil(log2(B)) — pointer jumping over a
    monotone parent array reaches the fixpoint in log rounds.
    """
    B = rep.shape[0]
    idx = jnp.arange(B, dtype=jnp.int32)
    agree = (sig == jnp.take(sig, rep, axis=0)).mean(axis=1)
    rep = jnp.where((agree >= threshold) & valid, rep, idx)
    for _ in range(jump_rounds):
        rep = jnp.take(rep, rep)
    return rep


@partial(jax.jit, static_argnames=("nbins",))
def bucket_histogram(
    keys: jnp.ndarray, valid: jnp.ndarray, *, nbins: int = 1 << 16
) -> jnp.ndarray:
    """Histogram of band keys over ``nbins`` — the psum-able dense summary
    used for cross-shard bucket-merge statistics (north star names
    ``lax.psum`` for this merge; see ``parallel/sharded.py``)."""
    flat = (keys % jnp.uint32(nbins)).astype(jnp.int32).reshape(-1)
    w = jnp.broadcast_to(valid[:, None], keys.shape).reshape(-1).astype(jnp.int32)
    return jnp.zeros((nbins,), dtype=jnp.int32).at[flat].add(w)


def keep_mask(rep: jnp.ndarray) -> jnp.ndarray:
    """True for rows that are their own representative (first seen)."""
    return rep == jnp.arange(rep.shape[0], dtype=rep.dtype)
