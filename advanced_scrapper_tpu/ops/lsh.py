"""16-band LSH bucketing and device-side first-seen-wins deduplication.

The reference dedups with pandas ``drop_duplicates(keep='first')``
(``yahoo_links_selenium.py:79,174``) — a hash-table walk on one CPU core.
The TPU formulation turns "same bucket" into a *sort*: for every band,
rows are sorted by (band key, row index); equal-key runs are bucket
collisions, and the run head (smallest row index — i.e. first seen) becomes
every member's candidate representative.  A signature-agreement check
verifies candidates, and log₂(B) rounds of pointer jumping resolve chains so
the final representative array has union-find semantics — all without
leaving the device or introducing data-dependent shapes.

Sorting is the idiomatic XLA substitute for hash tables: ``lax.sort`` is a
fused multi-operand bitonic sort that tiles well on TPU, whereas scattered
hash-table updates would serialise.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from advanced_scrapper_tpu.ops.shingle import FNV_OFFSET, FNV_PRIME, U32_MAX, fmix32


# second-lane constants for the wide (64-bit-entropy) band keys: a distinct
# FNV-style offset/prime pair so the two lanes are independent hashes of the
# same band content (TPUs have no native uint64 — the packing happens on
# host).  numpy (not jnp) scalars: a module-level jnp constant would
# initialise the backend at import time, breaking jax.distributed ordering.
import numpy as _np

_WIDE_OFFSET = _np.uint32(0xCBF29CE4)
_WIDE_PRIME = _np.uint32(0x01000197)


def _fold_bands(sig: jnp.ndarray, nb: int, offset, prime) -> jnp.ndarray:
    """FNV-1a fold of each band's signature rows → uint32[B, nb] (unsalted).

    Single source of the fold used by BOTH :func:`band_keys` and lane 0/1
    of :func:`band_keys_wide`, so their documented equivalence is
    structural, not maintained by parallel editing.
    """
    B, P = sig.shape
    r = P // nb
    rows = sig.reshape(B, nb, r)
    k = jnp.full((B, nb), offset, dtype=jnp.uint32)
    for j in range(r):
        k = (k ^ rows[:, :, j]) * prime
    return k


@jax.jit
def band_keys(sig: jnp.ndarray, band_salt: jnp.ndarray) -> jnp.ndarray:
    """Fold each band's rows into one salted uint32 bucket key.

    ``sig`` is ``uint32[B, num_perm]``; returns ``uint32[B, num_bands]``.
    The north-star config is 16 bands × 8 rows (BASELINE.json).
    """
    nb = band_salt.shape[0]
    k = _fold_bands(sig, nb, FNV_OFFSET, FNV_PRIME)
    return fmix32(k ^ band_salt[None, :])


@jax.jit
def band_keys_wide(sig: jnp.ndarray, band_salt: jnp.ndarray) -> jnp.ndarray:
    """uint32[B, num_bands, 2]: two independent 32-bit keys per band.

    Lane 0 is exactly :func:`band_keys` (same fold, same salt).  Lane 1
    folds the same band rows with different constants and a rotated salt.
    Packed to uint64 on host (``utils.bloom.pack_keys64``) this gives band
    keys whose accidental collision rate is ~n·num_bands/2⁶⁴ — required by
    the unattributed Bloom stream index, where a key collision is an
    unverifiable false drop (32-bit keys lose ~n/2³² of unique docs, ~4%
    at 10M scale).
    """
    nb = band_salt.shape[0]
    lo = _fold_bands(sig, nb, FNV_OFFSET, FNV_PRIME)
    hi = _fold_bands(sig, nb, _WIDE_OFFSET, _WIDE_PRIME)
    salt = band_salt[None, :]
    rot = (salt << jnp.uint32(13)) | (salt >> jnp.uint32(19))
    return jnp.stack([fmix32(lo ^ salt), fmix32(hi ^ rot)], axis=-1)


def _run_head_per_band(
    kt: jnp.ndarray, idxb: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """For each band row (axis 1 = batch): sorted keys → run-head,
    run-predecessor, and run-predecessor² indices,
    ``(si, head_sorted, pred_sorted, pred2_sorted)``.

    The predecessor² link (two sorted positions back WITHIN the run, self
    otherwise) exists to jump one failing intermediate: in a bucket run
    ``[d, x, e, y]`` where the decoys ``d``/``e`` verify against nobody,
    head and pred links leave ``x``—``y`` unconnected even though both are
    bucket members (datasketch candidacy) with agreement ≥ threshold —
    pred² links ``y`` straight to ``x``.  Measured on the hardened knee
    corpus this closes most of the co-bucketed recall the fine-only
    bridge edges used to carry (tools/sweep_fine_margin.py, DESIGN.md).
    """
    nb, B = kt.shape
    sk, si = jax.lax.sort((kt, idxb), dimension=1, num_keys=2)
    seg_start = jnp.concatenate(
        [jnp.ones((nb, 1), dtype=bool), sk[:, 1:] != sk[:, :-1]], axis=1
    )
    seg_id = jnp.cumsum(seg_start, axis=1) - 1  # int32 [nb, B], < B
    # si is ascending within each equal-key run, so the run head (first-seen
    # row) is the segment minimum of si, and the run predecessor is si
    # shifted one sorted position (self at run starts).
    run_min = jax.vmap(
        lambda s, g: jax.ops.segment_min(s, g, num_segments=B)
    )(si, seg_id)
    head_sorted = jnp.take_along_axis(run_min, seg_id, axis=1)
    pred_sorted = jnp.where(
        seg_start, si, jnp.concatenate([si[:, :1], si[:, :-1]], axis=1)
    )
    shift2 = jnp.concatenate([si[:, :2], si[:, :-2]], axis=1)
    same_run2 = jnp.concatenate(
        [jnp.zeros((nb, 2), dtype=bool), seg_id[:, 2:] == seg_id[:, :-2]],
        axis=1,
    )
    pred2_sorted = jnp.where(same_run2, shift2, si)
    return si, head_sorted, pred_sorted, pred2_sorted


@jax.jit
def duplicate_reps(keys: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Candidate representative per row: smallest earlier row sharing any band.

    Args:
      keys: ``uint32[B, num_bands]`` band bucket keys.
      valid: ``bool[B]`` — rows with no shingles (or batch padding) are
        excluded and map to themselves.

    Returns ``int32[B]`` with ``rep[i] <= i``; ``rep[i] == i`` means no
    earlier collision.  Candidates still need signature verification
    (:func:`resolve_reps`) — band collisions can be accidental.
    """
    B, nb = keys.shape
    idx = jnp.arange(B, dtype=jnp.int32)
    keys = jnp.where(valid[:, None], keys, U32_MAX)
    kt = keys.T
    idxb = jnp.broadcast_to(idx, (nb, B))
    si, rep_sorted, _pred, _pred2 = _run_head_per_band(kt, idxb)
    rep_band = jax.vmap(
        lambda s, r: jnp.zeros((B,), dtype=jnp.int32).at[s].set(r)
    )(si, rep_sorted)
    rep = rep_band.min(axis=0)
    # Invalid rows were all assigned key U32_MAX and may have grouped with
    # each other; sever them (and protect the pathological valid row that
    # really hashes to U32_MAX) by self-assignment.
    return jnp.where(valid, rep, idx)


@jax.jit
def duplicate_rep_bands(keys: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Per-band candidate representatives: ``int32[B, 3*nb]`` (run head +
    run predecessor + run predecessor² per band).

    Unlike :func:`duplicate_reps` (which min-reduces across bands BEFORE
    verification), this keeps every band's candidates independent so the
    verifier can test all of them.  The min-first scheme loses verified
    pairs to shadowing: if row i shares band 3 with its true near-dup j
    but band 7 accidentally collides with an unrelated earlier row h < j,
    min picks h, verification fails, and i reverts to self even though j
    would have verified (measured: 54 of 133 recall-certification misses
    were this exact shape).
    """
    B, nb = keys.shape
    idx = jnp.arange(B, dtype=jnp.int32)
    keys = jnp.where(valid[:, None], keys, U32_MAX)
    kt = keys.T
    idxb = jnp.broadcast_to(idx, (nb, B))
    # Head links alone under-connect a run — i and j may verify against
    # each other but not against the head (datasketch's union-find merges
    # any pairwise path); predecessor links chain consecutive run members
    # so those pairs survive, and predecessor² links jump one failing
    # intermediate (see _run_head_per_band).
    si, head_sorted, pred_sorted, pred2_sorted = _run_head_per_band(kt, idxb)
    cands = []
    for cand_sorted in (head_sorted, pred_sorted, pred2_sorted):
        cand = jax.vmap(
            lambda s, r: jnp.zeros((B,), dtype=jnp.int32).at[s].set(r)
        )(si, cand_sorted)
        cands.append(jnp.where(valid[None, :], cand, idxb).T)
    return jnp.concatenate(cands, axis=1)  # int32[B, 3*nb]


@partial(jax.jit, static_argnames=("jump_rounds",))
def resolve_rep_bands(
    rep_bands: jnp.ndarray,
    sig: jnp.ndarray,
    valid: jnp.ndarray,
    threshold: float,
    *,
    jump_rounds: int,
) -> jnp.ndarray:
    """Verify EVERY band candidate by signature agreement, keep the smallest
    verified one, then pointer-jump chains to the fixpoint.

    The multi-candidate twin of :func:`resolve_reps`: ``rep_bands`` is
    ``int32[B, nc]`` from :func:`duplicate_rep_bands` (callers may
    concatenate extra candidate sets along axis 1).  Each verified
    (row, candidate) pair is an undirected edge; the result is the
    connected-component minimum — exactly datasketch's union-find over
    verified pairs.  Single-parent min-hooking (keep only the smallest
    verified candidate, then pointer-jump) is NOT equivalent: a row with
    two verified edges keeps one, the discarded edge can bridge two
    clusters, and backward-only edges never pull a cluster's later rows
    down to its final label (measured: 30 of 74 certification misses had
    pairwise agreement ≥ threshold yet landed in different clusters).
    Label propagation: pull the min label along edges, push it back with a
    scatter-min, then pointer-double — symmetric, monotone, and fixpoint =
    component min within ``jump_rounds`` ≥ ceil(log2(B)) rounds.
    A merge still requires signature agreement — candidates that fail
    verification contribute no edge.  ``threshold`` may be a scalar, a
    per-candidate-COLUMN ``float32[nc]`` vector, or a per-EDGE
    ``float32[B, nc]`` array (:func:`fine_edge_thresholds`): fine-only
    edges — pairs sharing no
    coarse band, which datasketch's banding never proposes — verify
    against a higher bar, recovering the precision their extra candidacy
    gives up (measured sweep in DESIGN.md).
    """
    B, nc = rep_bands.shape
    idx = jnp.arange(B, dtype=jnp.int32)
    thr = jnp.asarray(threshold, jnp.float32)
    thr = jnp.broadcast_to(thr, (nc,) if thr.ndim < 2 else (B, nc))
    # Verify in candidate-axis chunks: the full [B, nc, P] gather would be
    # ~nc× the signature footprint (51 GB at nc=96 over a 2^20 bucket);
    # chunked, the peak transient stays at [B, 8, P] — the same order as
    # the signatures themselves.
    ok_parts = []
    for c0 in range(0, nc, 8):
        cand_sig = jnp.take(sig, rep_bands[:, c0 : c0 + 8], axis=0)
        agree = (sig[:, None, :] == cand_sig).mean(axis=2)
        thr_c = thr[..., c0 : c0 + 8]
        ok_parts.append(agree >= (thr_c if thr_c.ndim == 2 else thr_c[None, :]))
    # an edge needs BOTH endpoints valid: invalid rows (padding, sub-k
    # texts) must neither merge nor be merged into, structurally — not
    # just because their all-U32_MAX signatures happen to disagree
    ok = (
        jnp.concatenate(ok_parts, axis=1)
        & valid[:, None]
        & jnp.take(valid, rep_bands)
    )
    return _label_components(rep_bands, ok, valid, jump_rounds)


def _label_components(rep_bands, ok, valid, jump_rounds: int):
    """Connected-component min labels over the ``ok`` edge set (the shared
    back half of :func:`resolve_rep_bands` / :func:`resolve_rep_bands_from_ok`)."""
    B, nc = rep_bands.shape
    idx = jnp.arange(B, dtype=jnp.int32)
    cand = jnp.where(ok, rep_bands, idx[:, None])  # self-edges are no-ops
    lab = idx
    for _ in range(jump_rounds):
        pulled = jnp.take(lab, cand, axis=0).min(axis=1)
        lab = jnp.minimum(lab, pulled)
        lab = lab.at[cand.reshape(-1)].min(
            jnp.broadcast_to(lab[:, None], (B, nc)).reshape(-1)
        )
        lab = jnp.take(lab, lab)  # pointer doubling
    return jnp.where(valid, lab, idx)


@partial(jax.jit, static_argnames=("jump_rounds",))
def resolve_rep_bands_from_ok(
    rep_bands: jnp.ndarray,
    ok: jnp.ndarray,
    valid: jnp.ndarray,
    *,
    jump_rounds: int,
) -> jnp.ndarray:
    """:func:`resolve_rep_bands` with the verified-edge matrix supplied.

    For callers that already computed the agreement pass (e.g.
    :func:`borderline_edge_mask`) and edited it on host (the exact-verify
    stage kills refuted edges) — re-running the chunked signature gathers
    would double the heaviest device op on the one-shot path.
    """
    return _label_components(rep_bands, ok, valid, jump_rounds)


def subband_salt(num: int, seed: int = 0x5B5C9A02) -> _np.ndarray:
    """Deterministic uint32[num] salts for sub-band candidate keys —
    derived, not stored in MinHashParams, so any sub-band count works
    against the frozen north-star params."""
    x = (_np.arange(num, dtype=_np.uint64) * _np.uint64(0x9E3779B97F4A7C15)
         + _np.uint64(seed)) & _np.uint64(0xFFFFFFFF)
    return x.astype(_np.uint32)


def candidate_keys(
    sig: jnp.ndarray, band_salt, cand_subbands: int
) -> jnp.ndarray:
    """Coarse + fine candidate band keys: ``uint32[B, nb + cand_subbands]``.

    The single construction shared by the batch engine, the sharded step,
    and the driver entry — their resolutions must stay identical (the
    streamed path may not recall less than the certified one-shot path), so
    the key scheme lives in exactly one place.  Fine sub-bands (fewer rows
    per key) give near-certain candidacy at the threshold knee; merges
    still require signature-agreement verification, so precision is
    unchanged.  ``cand_subbands=0`` yields the plain 16-band keys.
    """
    keys = band_keys(sig, jnp.asarray(band_salt))
    if not cand_subbands:
        return keys
    num_perm = sig.shape[-1]
    if num_perm % cand_subbands:
        raise ValueError(
            f"cand_subbands {cand_subbands} must divide num_perm {num_perm} "
            "(each sub-band folds num_perm/cand_subbands signature rows)"
        )
    fine = band_keys(sig, jnp.asarray(subband_salt(cand_subbands)))
    return jnp.concatenate([keys, fine], axis=1)


def _maybe_densify(sig: jnp.ndarray, densify_oph: bool) -> jnp.ndarray:
    """OPH accumulators arrive RAW (empty bins ``U32_MAX``) so the
    streamed min-combine stays exact; the epilogues densify once, after
    the combine, inside their own dispatch."""
    if not densify_oph:
        return sig
    from advanced_scrapper_tpu.ops.oph import densify

    return densify(sig)


def _coarse_fine_keys(
    sig: jnp.ndarray, band_salt: jnp.ndarray, fine_salt: jnp.ndarray
) -> jnp.ndarray:
    """:func:`candidate_keys`' fold with the fine salts passed as an
    array — a zero-length ``fine_salt`` (static shape under trace)
    yields the plain coarse keys.  Shared by the fused epilogues so the
    key scheme still lives in exactly one construction."""
    keys = band_keys(sig, band_salt)
    if fine_salt.shape[0]:
        keys = jnp.concatenate([keys, band_keys(sig, fine_salt)], axis=1)
    return keys


@partial(jax.jit, static_argnames=("densify_oph",))
def fused_candidate_epilogue(
    sig_acc: jnp.ndarray,
    valid: jnp.ndarray,
    band_salt: jnp.ndarray,
    fine_salt: jnp.ndarray,
    *,
    densify_oph: bool,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """ONE-dispatch corpus epilogue: ``(sigs, keys, rep_bands)`` from the
    device-resident signature accumulator.

    Folds what used to be separate jitted calls — OPH densify,
    :func:`candidate_keys` (itself two ``band_keys`` dispatches when
    sub-bands are on) and :func:`duplicate_rep_bands` — into a single
    step, so a full corpus through the packed dedup path is
    ``tiles × 1`` dispatches plus this epilogue (ISSUE 9 / SEDD's
    launch-count argument).  ``fine_salt`` is ``subband_salt(cand_subbands)``
    or a zero-length array (its static shape selects the variant).
    """
    sig = _maybe_densify(sig_acc, densify_oph)
    keys = _coarse_fine_keys(sig, band_salt, fine_salt)
    return sig, keys, duplicate_rep_bands(keys, valid)


@partial(
    jax.jit,
    static_argnames=(
        "densify_oph", "num_coarse", "jump_rounds", "use_fine_margin",
    ),
)
def fused_resolve_epilogue(
    sig_acc: jnp.ndarray,
    valid: jnp.ndarray,
    band_salt: jnp.ndarray,
    fine_salt: jnp.ndarray,
    base,
    fine_margin,
    *,
    densify_oph: bool,
    num_coarse: int,
    jump_rounds: int,
    use_fine_margin: bool,
) -> jnp.ndarray:
    """The WHOLE estimator-only resolution as one dispatch: OPH densify →
    coarse+fine keys → per-band candidates → (optional) per-edge fine
    bars → verification + union-find labels.

    The async/firehose path (``dedup_reps_async`` with no rerank hook)
    rides this, so a full corpus is exactly ``tiles × 1`` dispatches plus
    this single epilogue — the ISSUE 9 launch-count shape.  A rerank hook
    needs the candidate matrix on the host boundary between candidates
    and resolution, so hooked engines fall back to the two-stage
    :func:`fused_candidate_epilogue` + :func:`resolve_rep_bands` split
    (identical math, one extra dispatch).
    """
    sig = _maybe_densify(sig_acc, densify_oph)
    keys = _coarse_fine_keys(sig, band_salt, fine_salt)
    rep_bands = duplicate_rep_bands(keys, valid)
    if use_fine_margin:
        thr = fine_edge_thresholds(
            rep_bands, keys, base, fine_margin, num_coarse=num_coarse
        )
    else:
        thr = base
    return resolve_rep_bands(
        rep_bands, sig, valid, thr, jump_rounds=jump_rounds
    )


@partial(jax.jit, static_argnames=("densify_oph", "wide"))
def fused_keys_epilogue(
    sig_acc: jnp.ndarray,
    band_salt: jnp.ndarray,
    fine_salt: jnp.ndarray,
    *,
    densify_oph: bool,
    wide: bool,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """ONE-dispatch ``(sigs, keys)`` epilogue for callers that join on
    host (the streaming batch backend) or feed a persistent index.

    ``wide=False`` returns :func:`candidate_keys`-equivalent coarse+fine
    keys; ``wide=True`` returns :func:`band_keys_wide`'s two-lane keys
    (``fine_salt`` ignored).  Replaces the old shape where the backend
    synced host signatures and passed them BACK through ``band_keys*`` —
    a D2H → re-H2D bounce plus extra dispatches per batch.
    """
    sig = _maybe_densify(sig_acc, densify_oph)
    if wide:
        return sig, band_keys_wide(sig, band_salt)
    return sig, _coarse_fine_keys(sig, band_salt, fine_salt)


def _fine_only_chunks(rep_bands, keys, num_coarse):
    """Yield ``(c0, cand_slice, fine_only_slice)`` in 8-column chunks:
    ``fine_only[b, c]`` is True when column c's candidate for row b shares
    NO coarse band with row b (i.e. the pair is outside datasketch's
    candidacy class).  Chunked so the gathered-coarse transient stays
    ``[B, 8, nb]``."""
    B, ncols = rep_bands.shape
    nbands = keys.shape[1]
    assert ncols % nbands == 0, (ncols, nbands)
    coarse = keys[:, :num_coarse]
    is_fine = _np.tile(_np.arange(nbands) >= num_coarse, ncols // nbands)
    for c0 in range(0, ncols, 8):
        cand = rep_bands[:, c0 : c0 + 8]
        fine_cols = is_fine[c0 : c0 + 8]
        if not fine_cols.any():
            yield c0, cand, jnp.zeros(cand.shape, bool)
            continue
        cand_coarse = jnp.take(coarse, cand, axis=0)  # [B, <=8, nbc]
        shared = (coarse[:, None, :] == cand_coarse).any(axis=2)
        yield c0, cand, ~shared & jnp.asarray(fine_cols)[None, :]


@partial(jax.jit, static_argnames=("num_coarse",))
def borderline_edge_mask(
    rep_bands: jnp.ndarray,
    sig: jnp.ndarray,
    keys: jnp.ndarray,
    valid: jnp.ndarray,
    base: float,
    band: float,
    *,
    num_coarse: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """``(need bool[B, nc], ok bool[B, nc])``: edges that pass estimator
    verification, and which of them should be confirmed by EXACT Jaccard
    before resolution.

    An edge needs exact confirmation (``need``) when its agreement clears
    ``base`` (it would merge) AND it is statistically fragile: either
    **fine-only** (outside datasketch's candidacy class — proposed by a
    fine sub-band with no shared coarse band, any agreement), or
    **coarse-borderline** (agreement < ``band``, where estimator noise
    σ≈0.04 at 128 perms makes true-J<threshold merges likely).
    Non-edges (self-candidates, invalid endpoints) are never flagged.
    ``ok`` is the full verified-edge matrix at ``base`` — callers edit it
    with the exact verdicts and resolve via
    :func:`resolve_rep_bands_from_ok`, so the chunked agreement gathers
    (the heaviest op in the resolve path) run ONCE.  See
    ``pipeline.dedup.NearDupEngine`` for the host exact-verify stage
    (measured budget: DESIGN.md §2e).
    """
    B, nc = rep_bands.shape
    idx = jnp.arange(B, dtype=jnp.int32)
    need_parts = []
    ok_parts = []
    for c0, cand, fine_only in _fine_only_chunks(rep_bands, keys, num_coarse):
        cand_sig = jnp.take(sig, cand, axis=0)
        agree = (sig[:, None, :] == cand_sig).mean(axis=2)
        is_edge = (
            (cand != idx[:, None])
            & valid[:, None]
            & jnp.take(valid, cand)
            & (agree >= base)
        )
        need_parts.append(is_edge & (fine_only | (agree < band)))
        ok_parts.append(
            (agree >= base) & valid[:, None] & jnp.take(valid, cand)
        )
    return (
        jnp.concatenate(need_parts, axis=1),
        jnp.concatenate(ok_parts, axis=1),
    )


@partial(jax.jit, static_argnames=("num_coarse",))
def fine_edge_thresholds(
    rep_bands: jnp.ndarray,
    keys: jnp.ndarray,
    base: float,
    fine_margin: float,
    *,
    num_coarse: int,
) -> jnp.ndarray:
    """Per-edge verification bars: ``float32[B, nc]`` for
    :func:`resolve_rep_bands`.

    Fine sub-bands serve two distinct edge classes and the precision
    budget (VERDICT r4 item 4) needs them separated:

    - **coarse-co-bucketed** fine edges — the row and its candidate share
      ≥1 coarse band, i.e. the pair is in datasketch's own candidacy
      class; the fine run merely recovered linkage the coarse
      run-head/predecessor scheme under-connects (≥3 interleaved bucket
      members).  These verify at ``base``: dropping or raising them costs
      exactly the knee recall the sub-bands exist to provide.
    - **fine-only** edges — no shared coarse band: pairs datasketch never
      proposes.  Some are true transitive bridges (high agreement), many
      are estimator noise just over the bar (the r4 ~3.2-point precision
      giveback — σ≈0.04 at 128 perms).  These verify at
      ``base + fine_margin``: strong bridges survive, noise dies.
      (Measured: gating them out entirely overshoots — precision −0.003
      vs oracle but recall 0.9255; ``tools/sweep_fine_margin.py``.)

    ``rep_bands`` is ``int32[B, 3·(nb+cs)]`` over :func:`candidate_keys`
    output (run heads for all bands, then run predecessors, then run
    predecessors²); ``keys`` the same ``uint32[B, nb+cs]`` the candidates
    came from; ``num_coarse`` = nb.  Gathers are chunked like
    :func:`resolve_rep_bands`'s so the transient stays ``[B, 8, nb]``.
    """
    base = jnp.float32(base)
    strict = jnp.float32(base + fine_margin)
    out = [
        jnp.where(fine_only, strict, base)
        for _c0, _cand, fine_only in _fine_only_chunks(
            rep_bands, keys, num_coarse
        )
    ]
    return jnp.concatenate(out, axis=1)


@partial(jax.jit, static_argnames=("jump_rounds",))
def resolve_reps(
    rep: jnp.ndarray,
    sig: jnp.ndarray,
    valid: jnp.ndarray,
    threshold: float,
    *,
    jump_rounds: int,
) -> jnp.ndarray:
    """Verify candidates by signature agreement, then resolve chains.

    ``agreement = mean(sig_i == sig_rep)`` is the standard unbiased MinHash
    Jaccard estimate; candidates below ``threshold`` revert to self.
    ``jump_rounds`` should be ≥ ceil(log2(B)) — pointer jumping over a
    monotone parent array reaches the fixpoint in log rounds.
    """
    B = rep.shape[0]
    idx = jnp.arange(B, dtype=jnp.int32)
    agree = (sig == jnp.take(sig, rep, axis=0)).mean(axis=1)
    rep = jnp.where((agree >= threshold) & valid, rep, idx)
    for _ in range(jump_rounds):
        rep = jnp.take(rep, rep)
    return rep


@partial(jax.jit, static_argnames=("nbins",))
def bucket_histogram(
    keys: jnp.ndarray, valid: jnp.ndarray, *, nbins: int = 1 << 16
) -> jnp.ndarray:
    """Histogram of band keys over ``nbins`` — the psum-able dense summary
    used for cross-shard bucket-merge statistics (north star names
    ``lax.psum`` for this merge; see ``parallel/sharded.py``)."""
    flat = (keys % jnp.uint32(nbins)).astype(jnp.int32).reshape(-1)
    w = jnp.broadcast_to(valid[:, None], keys.shape).reshape(-1).astype(jnp.int32)
    return jnp.zeros((nbins,), dtype=jnp.int32).at[flat].add(w)


def keep_mask(rep: jnp.ndarray) -> jnp.ndarray:
    """True for rows that are their own representative (first seen)."""
    return rep == jnp.arange(rep.shape[0], dtype=rep.dtype)
