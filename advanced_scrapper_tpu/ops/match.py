"""TPU q-gram screen for entity→article matching.

``match_keywords.py:159-180`` scans O(articles × tickers × names) strings on
CPU — regex word-boundary for ALL-CAPS names, ``rapidfuzz.partial_ratio >
95`` otherwise.  The TPU rerouting keeps the *decisions* on the host (so
CSV outputs stay byte-identical) but eliminates almost all of the quadratic
scanning with a device-side **no-false-negative screen**:

1. each article's q-gram set is hashed into a 2¹⁵-bit bitmap on device
   (one scatter per gram position);
2. each entity name's q-gram hash indices are gathered from every article's
   bitmap; an (article, name) pair survives only if enough name-grams are
   present.

Soundness thresholds (why the screen can't drop a true match):

- **exact/ALL-CAPS path**: a regex word-boundary hit implies the name is a
  substring, so ALL its ``m-q+1`` grams appear in the article → require all.
- **fuzzy path**: ``partial_ratio(article, name) > 95`` means some window
  ``w`` (``|w| ≤ m``) has indel distance ``d < 0.05·(m+|w|) ≤ 0.1·m``.
  One indel edit destroys at most q of the name's grams (q-gram lemma), so
  at least ``(m-q+1) - q·⌊0.1·m⌋`` name-grams must appear → require that.

Bloom collisions and window-vs-whole-article relaxation only ADD candidates
(false positives are later killed by exact host verification); they never
remove true ones.  Names too short to carry grams are always candidates.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from advanced_scrapper_tpu.core.hashing import gram_hashes_np
from advanced_scrapper_tpu.ops.shingle import shingle_hash

NBITS = 1 << 15
DEFAULT_Q = 3


def prepare_names(
    names: list[bytes], q: int = DEFAULT_Q, *, fuzzy: np.ndarray | None = None,
    nbits: int = NBITS, max_grams: int = 96,
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: names → (gram bit indices int32[N, max_grams] padded -1,
    required counts int32[N]).

    ``fuzzy[i]`` selects the fuzzy threshold for name i (else exact/all
    grams).  Names with no grams get required=0 → always candidates.
    """
    n = len(names)
    fuzzy = np.zeros(n, bool) if fuzzy is None else np.asarray(fuzzy, bool)
    grams = np.full((n, max_grams), -1, dtype=np.int32)
    required = np.zeros(n, dtype=np.int32)
    for i, raw in enumerate(names):
        h = gram_hashes_np(raw, q)
        g = (h % nbits).astype(np.int32)[:max_grams]
        grams[i, : len(g)] = g
        m = len(raw)
        total = max(0, m - q + 1)
        if total == 0:
            required[i] = 0
        elif fuzzy[i]:
            # q-gram lemma bound for ratio > 95 (see module docstring)
            required[i] = max(1, min(len(g), total - q * int(0.1 * m)))
        else:
            required[i] = len(g)  # substring ⇒ every (kept) gram present
    return grams, required


@partial(jax.jit, static_argnames=("nbits", "q"))
def _screen_impl(tokens, lengths, name_grams, name_required, *, nbits: int, q: int):
    h, valid = shingle_hash(tokens, lengths, q)
    idx = jnp.where(valid, (h % jnp.uint32(nbits)).astype(jnp.int32), nbits)
    B = tokens.shape[0]
    bitmap = jnp.zeros((B, nbits), dtype=bool)
    bitmap = jax.vmap(lambda bm, ix: bm.at[ix].set(True, mode="drop"))(bitmap, idx)
    # gather name gram bits from every article's bitmap: [B, N, G]
    safe = jnp.maximum(name_grams, 0)
    present = jax.vmap(lambda bm: bm[safe])(bitmap)
    present = present & (name_grams >= 0)[None, :, :]
    counts = present.sum(axis=-1).astype(jnp.int32)
    return counts >= name_required[None, :]


def match_screen(
    tokens: np.ndarray,
    lengths: np.ndarray,
    name_grams: np.ndarray,
    name_required: np.ndarray,
    *,
    nbits: int = NBITS,
    q: int = DEFAULT_Q,
) -> np.ndarray:
    """``bool[B, N]`` — True where (article, name) survives the screen."""
    return np.asarray(
        _screen_impl(
            tokens,
            lengths,
            jnp.asarray(name_grams),
            jnp.asarray(name_required),
            nbits=nbits,
            q=q,
        )
    )
