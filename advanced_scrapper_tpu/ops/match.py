"""TPU q-gram screen for entity→article matching.

``match_keywords.py:159-180`` scans O(articles × tickers × names) strings on
CPU — regex word-boundary for ALL-CAPS names, ``rapidfuzz.partial_ratio >
threshold`` otherwise.  The TPU rerouting keeps the *decisions* on the host
(so CSV outputs stay byte-identical) but eliminates almost all of the
quadratic scanning with a device-side **no-false-negative screen**:

1. each article's q-gram set (over ``title\\ntext``) is hashed into a
   2¹⁵-bit bitmap on device;
2. each entity name's q-gram hash indices are gathered from every article's
   bitmap; an (article, name) pair survives only if enough name-grams are
   present.

Soundness ("enough" can never prune a true match).  Let ``m = |name|``,
``D`` the length of the matched part (text or title — matching tries both,
so the screen takes the *weakest* bound over the two), ``e = min(D, m)``,
``t`` the fuzzy threshold, and ``d_max(e) = ⌊2e(1 - t/100)⌋`` the largest
indel distance any window alignment can have at score > t
(``score = 100·2·LCS/(m+|w|)`` and ``|w| ≤ e``).  By the q-gram lemma one
indel destroys at most q gram occurrences, so:

- **exact/ALL-CAPS path**: a word-boundary hit means the name is a substring
  of a part with ``D ≥ m`` and ALL its grams appear → require every kept
  gram, and prune outright when both parts are shorter than the name;
- **fuzzy, part at least name-sized (D ≥ m)**: at most ``q·d_max(m)`` of
  the name's gram occurrences miss the window → at least
  ``kept - q·d_max(m)`` of the *kept* grams appear in the part;
- **fuzzy, short part (D < m)**: the window is a ``D``-length slice of the
  name; its ``D-q+1`` gram positions lose at most ``q·d_max(D)`` to edits →
  require ``(D-q+1) - q·d_max(D)``.  This is only valid when no grams were
  truncated (a tail window may avoid the kept prefix entirely), so
  truncated names with short parts are never screened;
- any bound ≤ 0 → the pair always survives to host verification.

Bloom collisions and part-concatenation only ADD candidates; host
verification kills them, so screened output is golden-equal to unscreened
(tested, including adversarial short-title and truncated-name cases).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from advanced_scrapper_tpu.core.hashing import gram_hashes_np
from advanced_scrapper_tpu.ops.shingle import shingle_hash

NBITS = 1 << 15
DEFAULT_Q = 3
MAX_GRAMS = 96


def prepare_names(
    names: list[bytes],
    q: int = DEFAULT_Q,
    *,
    fuzzy: np.ndarray | None = None,
    nbits: int = NBITS,
    max_grams: int = MAX_GRAMS,
) -> dict:
    """Host-side name tables for :func:`match_screen`.

    Returns arrays: ``grams int32[N, max_grams]`` (bit indices, -1 padded),
    ``kept/total int32[N]`` gram counts, ``name_len int32[N]``,
    ``fuzzy bool[N]``.
    """
    n = len(names)
    fuzzy = np.zeros(n, bool) if fuzzy is None else np.asarray(fuzzy, bool)
    grams = np.full((n, max_grams), -1, dtype=np.int32)
    kept = np.zeros(n, dtype=np.int32)
    total = np.zeros(n, dtype=np.int32)
    name_len = np.zeros(n, dtype=np.int32)
    for i, raw in enumerate(names):
        h = gram_hashes_np(raw, q)
        g = (h % nbits).astype(np.int32)[:max_grams]
        grams[i, : len(g)] = g
        kept[i] = len(g)
        total[i] = len(h)
        name_len[i] = len(raw)
    return {
        "grams": grams,
        "kept": kept,
        "total": total,
        "name_len": name_len,
        "fuzzy": fuzzy.copy(),
    }


def _screen_core(
    tokens,
    text_len,
    title_len,
    doc_len,
    grams,
    kept,
    total,
    name_len,
    fuzzy,
    threshold,
    *,
    nbits: int,
    q: int,
):
    """Traceable screen body — shared by the standalone
    :func:`match_screen` dispatch and the packed single-dispatch
    :func:`make_screen_step` (where the name tables are closure
    constants folded into the compiled step)."""
    h, valid = shingle_hash(tokens, doc_len, q)
    idx = jnp.where(valid, (h % jnp.uint32(nbits)).astype(jnp.int32), nbits)
    B = tokens.shape[0]
    bitmap = jnp.zeros((B, nbits), dtype=bool)
    bitmap = jax.vmap(lambda bm, ix: bm.at[ix].set(True, mode="drop"))(bitmap, idx)
    safe = jnp.maximum(grams, 0)
    present = jax.vmap(lambda bm: bm[safe])(bitmap)          # [B, N, G]
    present = present & (grams >= 0)[None, :, :]
    count = present.sum(axis=-1).astype(jnp.int32)           # [B, N]

    frac = 2.0 * (1.0 - threshold / 100.0)
    m = name_len[None, :]                                    # [1, N]
    truncated = (kept < total)[None, :]

    def fuzzy_bound(D):                                      # D: [B] part lengths
        D = D[:, None]
        e = jnp.minimum(D, m)
        dmax = jnp.floor(e.astype(jnp.float32) * frac).astype(jnp.int32)
        dmax_m = jnp.floor(m.astype(jnp.float32) * frac).astype(jnp.int32)
        b_long = kept[None, :] - q * dmax_m                  # D >= m
        b_short = (D - q + 1) - q * dmax                     # D <  m, untruncated
        b_short = jnp.where(truncated, 0, b_short)
        return jnp.where(D >= m, b_long, b_short)

    req = jnp.minimum(fuzzy_bound(text_len), fuzzy_bound(title_len))
    fuzzy_keep = (req <= 0) | (count >= jnp.maximum(req, 1))

    part_max = jnp.maximum(text_len, title_len)[:, None]
    exact_keep = (count >= kept[None, :]) & (part_max >= m)

    return jnp.where(fuzzy[None, :], fuzzy_keep, exact_keep)


@partial(jax.jit, static_argnames=("nbits", "q"))
def _screen_impl(
    tokens,
    text_len,
    title_len,
    doc_len,
    grams,
    kept,
    total,
    name_len,
    fuzzy,
    threshold,
    *,
    nbits: int,
    q: int,
):
    return _screen_core(
        tokens, text_len, title_len, doc_len, grams, kept, total, name_len,
        fuzzy, threshold, nbits=nbits, q=q,
    )


#: int32 trailer planes of a packed screen tile, in order: combined
#: ``title\ntext`` length, text length, title length, per-row flags
#: (:data:`FLAG_REFINE_OK`), row→article owner (−1 = tail padding).
SCREEN_PLANES = 5

#: flags-plane bit: the row's text side is refine-eligible (non-empty,
#: pure ASCII, not overlong) — the byte-level Myers bound is only sound
#: against the char-level oracle on ASCII text, and that test is host-only.
FLAG_REFINE_OK = 1

#: survivor-mask bits returned by :func:`make_screen_step` (uint8[B, N]):
#: bit 0 = the (article, name) pair survives the q-gram screen; bit 1 =
#: the name's TEXT-side fuzzy score is device-proven ≤ threshold (Myers
#: bound; only ever set on refine-candidate columns).
MASK_SCREEN_KEEP = 1
MASK_TEXT_PRUNED = 2


def make_screen_step(
    tables: dict,
    refine: tuple | None = None,
    *,
    nbits: int = NBITS,
    q: int = DEFAULT_Q,
    refine_block: int = 512,
):
    """Build the SINGLE-dispatch packed screen step of the matcher path:
    ``(packed, threshold) -> (mask uint8[rows, N], owners int32[rows])``
    — unpack the one-buffer tile (``ops.pack``, :data:`SCREEN_PLANES`
    trailer planes), run the q-gram screen, and (with ``refine``) fold
    the Myers alignment bound into the SAME dispatch, all inside one
    jitted call.

    The legacy loop pays ≥2 puts and ≥2 dispatches per batch (screen
    arrays, then the bound kernel over host-gathered survivor pairs); on
    a tunneled transport each is a control-channel round trip.  Here the
    survivor mask never leaves the device between the two stages: the
    bound consumes it in-kernel and overwrites the refine-candidate
    columns with the prune verdict (:data:`MASK_TEXT_PRUNED`), so a tile
    is exactly 1 put + 1 dispatch — the matcher half of the PR 9
    launch-count ledger.

    ``refine = (masks uint32[K,256], plens int32[K], ok bool[K],
    cols int64[K])`` is a prebuilt ``editdist.build_pattern_masks``
    result plus the entry-column index of each refine candidate; the
    bound runs ALL (row, candidate) pairs via the shared-text kernel
    (``editdist.semiglobal_dist_shared`` — no ``B×K`` text
    materialisation) over the combined ``title\\ntext`` row.  Scanning
    the combined row only ever LOWERS the distance (more substrings), so
    the bound stays an upper bound on the text-side ``partial_ratio``
    and pruning on it stays sound; device-side gates (text strictly
    longer than the pattern, pattern ``ok``) mirror
    ``editdist.prune_mask_tables``, host-only gates ride the flags
    plane.  ``refine=None`` builds the screen-only variant — the
    refine-race controller (``pipeline.matcher.RefineController``) picks
    between the two compiled MODES, not between separate kernels.

    The name tables are closure-captured (constant-folded into the
    compiled step) so no per-tile table transfer exists; cache the
    returned callable per index (``pipeline.matcher`` holds one pair per
    ``EntityIndex``).  Compiled per static ``(rows, width)`` — callers
    keep both bucketed (O(log) shapes; ``pipeline.matcher``'s tile
    chunker and prewarm share one derivation).

    SENTINEL CONTRACT: the raw ``jax.jit`` object is returned (exposing
    ``_cache_size``) so ``pipeline.matcher._screen_steps`` can wrap it in
    the recompile sentinel (``obs.devprof.instrument_jit`` →
    ``astpu_jit_compiles_total{kernel="matcher_screen_step"}``; ops may
    not import obs — layering).
    """
    from advanced_scrapper_tpu.ops.pack import unpack_tile_planes

    grams = np.asarray(tables["grams"])
    kept = np.asarray(tables["kept"])
    total = np.asarray(tables["total"])
    name_len = np.asarray(tables["name_len"])
    fuzzy = np.asarray(tables["fuzzy"])
    if refine is not None and len(refine[3]) == 0:
        refine = None
    if refine is not None:
        r_masks, r_lens, r_ok, r_cols = (np.asarray(a) for a in refine)

    @partial(jax.jit, static_argnames=("rows", "width"))
    def screen_step(packed, threshold, *, rows: int, width: int):
        tok, planes = unpack_tile_planes(packed, rows, width, SCREEN_PLANES)
        doc_len, text_len, title_len, flags, owners = planes
        keep = _screen_core(
            tok, text_len, title_len, doc_len, grams, kept, total,
            name_len, fuzzy, threshold, nbits=nbits, q=q,
        )
        mask = keep.astype(jnp.uint8)
        if refine is not None:
            from advanced_scrapper_tpu.ops.editdist import (
                semiglobal_dist_shared,
            )

            d = semiglobal_dist_shared(
                r_masks, r_lens, tok, doc_len, block=refine_block
            )                                            # [rows, K]
            # bound = 100·(1 − d/(2m)) ≤ threshold, cleared of the
            # division: 100·d ≥ 2m·(100 − threshold).  Every operand is
            # a small-int product (d, m ≤ a few hundred), exact in f32.
            bound_pruned = (
                d.astype(jnp.float32) * 100.0
                >= 2.0 * r_lens[None, :].astype(jnp.float32)
                * (100.0 - threshold)
            )
            prunable = (
                r_ok[None, :]
                & (text_len[:, None] > r_lens[None, :])
                & ((flags & FLAG_REFINE_OK) != 0)[:, None]
                & bound_pruned
            )
            # the survivor mask is consumed and overwritten in-kernel:
            # refine-candidate columns gain the prune bit in place
            mask = mask.at[:, r_cols].set(
                mask[:, r_cols]
                | (prunable.astype(jnp.uint8) << 1)
            )
        return mask, owners

    return screen_step


def match_screen(
    tokens: np.ndarray,
    text_len: np.ndarray,
    title_len: np.ndarray,
    doc_len: np.ndarray,
    tables: dict,
    *,
    threshold: float = 95.0,
    nbits: int = NBITS,
    q: int = DEFAULT_Q,
) -> np.ndarray:
    """``bool[B, N]`` — True where (article, name) survives the screen.

    ``tokens/doc_len`` describe the combined ``title\\ntext`` byte rows;
    ``text_len``/``title_len`` are the raw part lengths the soundness bounds
    are computed from.
    """
    return np.asarray(
        _screen_impl(
            tokens,
            jnp.asarray(text_len, jnp.int32),
            jnp.asarray(title_len, jnp.int32),
            jnp.asarray(doc_len, jnp.int32),
            jnp.asarray(tables["grams"]),
            jnp.asarray(tables["kept"]),
            jnp.asarray(tables["total"]),
            jnp.asarray(tables["name_len"]),
            jnp.asarray(tables["fuzzy"]),
            jnp.float32(threshold),
            nbits=nbits,
            q=q,
        )
    )
