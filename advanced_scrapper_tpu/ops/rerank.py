"""Pair-settlement math for the device-batched rerank (precision) tier.

The LSH candidate matrix arriving on ``RERANK_HOOK_EDGE``
(``pipeline/dedup.py``) thresholds a 128-lane *estimator* (σ≈0.04), so
the merged-pair precision tops out around 0.85–0.89 against the ≥0.95
ambition.  This module holds the pure math the tier is built from — no
pipeline, runtime, index or obs imports (enforced by
``tools/lint_imports.py``; the orchestration half lives in
``pipeline/rerank.py``):

- **bottom-S shingle sketches** (:func:`bottom_sketch`): per document,
  the ``S`` smallest 32-bit-hashed k-byte shingles.  The pairwise
  Jaccard estimator built on two such sketches has σ≈√(J(1−J)/S)
  (≈0.014 at S=1024, 3× tighter than the 128-perm signature) and is
  EXACT whenever ``|union| ≤ S`` — i.e. for every document pair short
  enough that both shingle sets fit the sketch.
- the **vmap'd settle kernel** (:func:`make_rerank_tile_step`): one
  packed pair tile (``ops.pack.pack_pair_tile``) in, per-pair
  quantized Jaccard scattered into a device-resident fold buffer out —
  1 ``device_put`` + 1 dispatch per tile, verdicts read back ONCE per
  corpus after :func:`make_rerank_finalize`.
- the **candidacy + clustering host half**: coarse band-bucket pair
  recovery (:func:`coarse_pairs`, datasketch's candidacy class),
  vectorised signature agreement, union-find, and the
  precision-targeted eviction policy (:func:`evict_for_precision`)
  that trades the measured tail of false merges for the ≥0.95 pooled
  precision bar while a recall floor guards the other bar.
- a **host twin of the wide band keys**
  (:func:`band_keys_wide_host`): the borderline ANN re-probe consults
  the persistent index's segment postings, whose key space is
  ``ops.lsh.band_keys_wide`` — the twin reproduces it in numpy so the
  tier never pays a device dispatch for keys (parity is pinned in
  ``tests/test_rerank_dispatch.py``).

Quantization: Jaccard values cross the device boundary as
``round(J * SCALE)`` int32 (σ·SCALE ≈ 140 quanta, so the 1e-4 grid is
noise-free resolution) — integer verdicts are byte-stable across
put-worker/window orderings, which a float fold could not promise.
"""

from __future__ import annotations

import math

import numpy as np

from advanced_scrapper_tpu.ops.pack import pair_tile_nbytes, unpack_pair_tile
from advanced_scrapper_tpu.ops.shingle import FNV_OFFSET, FNV_PRIME

__all__ = [
    "PAD",
    "SCALE",
    "band_keys_wide_host",
    "bottom_sketch",
    "bottom_sketches",
    "coarse_pairs",
    "evict_for_precision",
    "make_rerank_finalize",
    "make_rerank_tile_step",
    "pair_tile_nbytes",
    "quantize",
    "rewrite_rep_bands",
    "signature_agreement",
    "sketch_jaccard",
    "union_find",
]

#: sketch padding sentinel — sorts after every real 32-bit hash, and real
#: hashes equal to it are dropped at build time so it is unambiguous
PAD = np.uint32(0xFFFFFFFF)

#: Jaccard quantization grid: device verdicts are ``round(J * SCALE)``
SCALE = 10_000

_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def quantize(j: float) -> int:
    """Host-side twin of the device quantization: ``round(j * SCALE)``."""
    return int(round(float(j) * SCALE))


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finaliser over uint64 — shingle ids → uniform hashes."""
    x = np.asarray(x, np.uint64)
    x = (x + np.uint64(0x9E3779B97F4A7C15)) & _M64
    x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) & _M64
    x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) & _M64
    return x ^ (x >> np.uint64(31))


# -- bottom-S sketches ------------------------------------------------------


def bottom_sketch(text: str | bytes, k: int, size: int) -> np.ndarray:
    """``uint32[size]`` bottom-``size`` sketch of the k-byte shingle set.

    Shingle semantics mirror ``cpu.oracle.shingle_set`` exactly (utf-8
    ``errors="replace"``, ``len < k`` → empty set → all-PAD sketch), so
    the sketch estimator converges on the oracle's TRUE Jaccard.  Ids
    are exact for ``k ≤ 8`` (bytes packed into uint64); longer shingles
    fold the tail bytes FNV-style.  All numpy, no per-shingle Python.
    """
    raw = (
        text.encode("utf-8", errors="replace")
        if isinstance(text, str)
        else bytes(text)
    )
    out = np.full((size,), PAD, np.uint32)
    if len(raw) < k:
        return out
    b = np.frombuffer(raw, np.uint8)
    win = np.lib.stride_tricks.sliding_window_view(b, k)
    ids = np.zeros(win.shape[0], np.uint64)
    for j in range(min(k, 8)):
        ids |= win[:, j].astype(np.uint64) << np.uint64(8 * j)
    for j in range(8, k):
        ids = ((ids * np.uint64(0x100000001B3)) & _M64) ^ win[:, j].astype(
            np.uint64
        )
    h = (_mix64(np.unique(ids)) >> np.uint64(32)).astype(np.uint32)
    h = np.unique(h)
    h = h[h != PAD]
    m = min(size, h.size)
    out[:m] = h[:m]
    return out


def bottom_sketches(
    texts, k: int, size: int, *, skip=None
) -> np.ndarray:
    """``uint32[n, size]`` stacked :func:`bottom_sketch` per document.
    ``skip`` (bool[n]) rows stay all-PAD without touching the text."""
    n = len(texts)
    out = np.full((n, size), PAD, np.uint32)
    for i in range(n):
        if skip is not None and skip[i]:
            continue
        out[i] = bottom_sketch(texts[i], k, size)
    return out


def sketch_jaccard(ska: np.ndarray, skb: np.ndarray) -> float:
    """Host reference estimator — the kernel's float twin (tests pin the
    quantized device verdict against ``quantize`` of this)."""
    size = int(ska.shape[0])
    a = ska[ska != PAD]
    b = skb[skb != PAD]
    if a.size == 0 and b.size == 0:
        return 1.0
    uni = np.union1d(a, b)
    kk = min(size, uni.size)
    if kk == 0:
        return 1.0
    inter = np.intersect1d(a, b)
    matches = int(np.isin(uni[:kk], inter, assume_unique=True).sum())
    return matches / kk


# -- the vmap'd settle kernel ----------------------------------------------


def _pair_jq(ca, cb, size: int):
    """Quantized bottom-sketch Jaccard of ONE pair (1-D uint32 sketches).

    Sorted-concat formulation: a value appearing twice is in both
    sketches (each sketch holds unique values); the union's bottom-kk
    is the first kk unique values of the sorted concat.  Everything is
    sort/cumsum — XLA-native, lane-aligned at 2·size per pair.
    """
    import jax.numpy as jnp

    pad = jnp.uint32(0xFFFFFFFF)
    c = jnp.sort(jnp.concatenate([ca, cb]))
    live = c != pad
    nxt = jnp.concatenate([c[1:], jnp.full((1,), pad, jnp.uint32)])
    dup = (c == nxt) & live
    first = jnp.concatenate([live[:1], (c[1:] != c[:-1]) & live[1:]])
    rank = jnp.cumsum(first.astype(jnp.int32)) - 1
    n_uni = jnp.sum(first.astype(jnp.int32))
    kk = jnp.minimum(n_uni, size)
    matches = jnp.sum((dup & (rank < kk)).astype(jnp.int32))
    # integer round-half-up of SCALE·matches/kk; empty∪empty ⇒ J=1
    # (oracle.jaccard's both-empty convention)
    return jnp.where(
        kk > 0,
        (SCALE * matches + kk // 2) // jnp.maximum(kk, 1),
        SCALE,
    ).astype(jnp.int32)


def make_rerank_tile_step(rows: int, sketch: int):
    """RAW jitted settle step for one packed pair tile —
    ``(fold int32[cap], packed uint8[pair_tile_nbytes]) → fold``.

    The fold buffer is donated (device-resident across tiles, one
    readback per corpus) and pad rows carry a fold slot ≥ cap, which the
    ``mode="drop"`` scatter discards.  Callers wrap the returned jit in
    the recompile sentinel (``obs.devprof.instrument_jit``) — this
    module stays obs-free by layering rule.
    """
    from functools import partial

    import jax

    @partial(jax.jit, donate_argnums=(0,))
    def rerank_tile_step(fold, packed):
        ska, skb, idx = unpack_pair_tile(packed, rows, sketch)
        jq = jax.vmap(lambda a, b: _pair_jq(a, b, sketch))(ska, skb)
        return fold.at[idx].set(jq, mode="drop")

    return rerank_tile_step


def make_rerank_finalize():
    """RAW jitted corpus finalize — ``(fold, lo, hi) → (fold, verdict)``.

    ``lo``/``hi`` are the quantized margin-band bounds passed as dynamic
    int32 scalars (ONE compile regardless of threshold/margin config —
    the recompile sentinel must stay zero in steady state).  Verdict
    int8 per slot: 1 keep (``jq ≥ hi``), 0 kill (``jq < lo``), -1
    borderline — re-settled on host (exact Jaccard / ANN re-probe).
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def rerank_finalize(fold, lo, hi):
        border = (fold >= lo) & (fold < hi)
        verdict = jnp.where(
            border, jnp.int8(-1), (fold >= hi).astype(jnp.int8)
        )
        return fold, verdict

    return rerank_finalize


# -- host candidacy / clustering / eviction policy -------------------------


def coarse_pairs(
    sigs: np.ndarray,
    valid: np.ndarray,
    num_bands: int,
    *,
    bucket_allpairs: int = 64,
) -> tuple[set, int]:
    """Datasketch-class candidate pairs from coarse LSH band buckets.

    Groups the ``num_bands`` band slices of ``sigs[:n]`` (host array,
    any integer dtype) by a mixed bucket key; every bucket of valid rows
    yields all ``(i < j)`` pairs up to ``bucket_allpairs`` members, and
    a star+chain (first-seen hub plus adjacent links, 2(m−1) pairs)
    above it — connectivity-preserving under union-find, so a giant
    boilerplate bucket cannot go quadratic.  Returns ``(pairs,
    n_capped_buckets)``; mixing can only MERGE buckets (never split), so
    candidacy is a superset of the oracle's — spurious pairs are settled
    by the sketch kernel downstream.
    """
    n = sigs.shape[0]
    r = sigs.shape[1] // num_bands
    pairs: set = set()
    capped = 0
    vidx = np.flatnonzero(np.asarray(valid[:n], bool))
    if vidx.size < 2:
        return pairs, capped
    sig = np.ascontiguousarray(sigs[vidx], np.uint64)
    for b in range(num_bands):
        key = np.full(vidx.size, np.uint64(b), np.uint64)
        for c in range(b * r, (b + 1) * r):
            key = _mix64(key ^ sig[:, c])
        order = np.argsort(key, kind="stable")
        sk = key[order]
        starts = np.flatnonzero(
            np.concatenate([[True], sk[1:] != sk[:-1]])
        )
        ends = np.concatenate([starts[1:], [sk.size]])
        for s, e in zip(starts, ends):
            if e - s < 2:
                continue
            members = np.sort(vidx[order[s:e]])
            m = members.size
            if m <= bucket_allpairs:
                for x in range(m):
                    for y in range(x + 1, m):
                        pairs.add((int(members[x]), int(members[y])))
            else:
                capped += 1
                hub = int(members[0])
                for x in range(1, m):
                    pairs.add((hub, int(members[x])))
                    if x + 1 < m:
                        pairs.add((int(members[x]), int(members[x + 1])))
    return pairs, capped


def signature_agreement(sigs: np.ndarray, pairs: np.ndarray) -> np.ndarray:
    """``float64[m]`` lane-agreement estimator per ``(i, j)`` pair row —
    vectorised ``cpu.oracle.estimated_jaccard``."""
    if pairs.shape[0] == 0:
        return np.zeros((0,), np.float64)
    return (sigs[pairs[:, 0]] == sigs[pairs[:, 1]]).mean(axis=1)


def union_find(n: int, edges) -> np.ndarray:
    """``int32[n]`` min-root component labels over undirected ``edges`` —
    the host twin of ``ops.lsh``'s on-device label propagation."""
    parent = np.arange(n, dtype=np.int64)

    def find(x: int) -> int:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    for i, j in edges:
        ri, rj = find(int(i)), find(int(j))
        if ri != rj:
            if ri > rj:
                ri, rj = rj, ri
            parent[rj] = ri
    return np.array([find(i) for i in range(n)], np.int32)


def op_weight(jhat: float, lanes: int, threshold: float = 0.7) -> float:
    """Recall-relevance weight of a pair: the probability a fresh
    ``lanes``-lane MinHash agreement draw at true Jaccard ≈ ``jhat``
    lands at or above ``threshold``.

    The recall bar is judged against an ESTIMATOR oracle (datasketch
    semantics): a pair enters the denominator when the oracle's own
    128-lane draw reads ≥ threshold, an event only probabilistically
    knowable from the settled truth.  Lane agreement is
    Binomial(lanes, J)/lanes, so the normal approximation
    ``Φ((jhat − threshold) / sqrt(jhat(1−jhat)/lanes))`` prices each
    pair's expected recall mass — a J=0.9 pair is certainly counted
    (w≈1), a settled-bad J=0.62 pair almost certainly is not (w≈0.03),
    and the borderline band prices in between.  The eviction policy
    sums these weights instead of counting binary estimator verdicts:
    the engine's OWN estimator draw is correlated with the oracle's
    only through the true J, so thresholding it misprices exactly the
    borderline pairs where recall is won or lost.
    """
    j = min(max(jhat, 0.02), 0.98)
    sigma = math.sqrt(j * (1.0 - j) / max(lanes, 1))
    return 0.5 * (1.0 + math.erf((jhat - threshold) / (sigma * math.sqrt(2.0))))


def evict_for_precision(
    clusters: dict,
    pairinfo: dict,
    target: float,
    *,
    recall_floor: float = 0.0,
    total_op_mass: float = 0.0,
) -> tuple[set, float]:
    """Greedy precision-targeted member eviction over settled clusters.

    ``clusters`` maps root → member list (size > 1); ``pairinfo`` maps
    each within-cluster ``(a < b)`` pair to ``(bad, w)`` — ``bad`` is
    the settled TRUE verdict (J < threshold: a false merge the
    precision metric counts against us), ``w`` the pair's expected
    recall mass (:func:`op_weight`: the probability the estimator
    oracle counts it).  Members are evicted one at a time — highest
    ``bad/(1+op_mass)`` first (ties: most recall-free bad pairs, then
    most bad pairs), only from clusters with ≥3 live members (pair
    clusters are all-or-nothing) — until the predicted merged-pair
    precision reaches ``target``.  The score is recall-aware by
    construction: a member whose bad pairs carry recall mass is
    expensive to evict, so the walk burns pure-loss pairs first.

    ``recall_floor`` (with ``total_op_mass``) is the hard guard for the
    other bar: eviction stops before predicted recall — live recall
    mass over the starting in-cluster mass — would cross below it.
    Returns ``(evicted member set, predicted precision)``.
    """
    memb: dict = {}
    good = bad = 0
    op_live = 0.0
    for (a, b), (is_bad, w) in pairinfo.items():
        good += not is_bad
        bad += is_bad
        op_live += w
        for d in (a, b):
            s = memb.setdefault(d, [0, 0.0, 0])  # bad, op_mass, badfree
            s[0] += is_bad
            s[1] += w
            s[2] += is_bad and w < 0.25
    evicted: set = set()

    def prec() -> float:
        return good / max(good + bad, 1)

    while bad and prec() < target:
        best = None
        for r, m in clusters.items():
            live = [d for d in m if d not in evicted]
            if len(live) < 3:
                continue
            for d in live:
                b_, o_, bf_ = memb.get(d, (0, 0.0, 0))
                if b_ == 0:
                    continue
                score = (b_ / (1.0 + o_), bf_, b_)
                if best is None or score > best[0]:
                    best = (score, d, r)
        if best is None:
            break
        _, d, r = best
        if total_op_mass and recall_floor:
            lost = memb.get(d, (0, 0.0, 0))[1]
            if (op_live - lost) / max(total_op_mass, 1e-9) < recall_floor:
                break
        evicted.add(d)
        for x in clusters[r]:
            if x in evicted or x == d:
                continue
            key = (d, x) if d < x else (x, d)
            is_bad, w = pairinfo[key]
            good -= not is_bad
            bad -= is_bad
            op_live -= w
            s = memb[x]
            s[0] -= is_bad
            s[1] -= w
            s[2] -= is_bad and w < 0.25
        memb[d] = [0, 0.0, 0]
    return evicted, prec()


def rewrite_rep_bands(
    n_bucket: int, nc: int, edges
) -> tuple[np.ndarray, int]:
    """``int32[n_bucket, nc]`` candidate matrix holding exactly ``edges``.

    The tier's output on ``RERANK_HOOK_EDGE``: all-self baseline, each
    surviving edge ``(i, j)`` lands on its LATER row (``max``'s row gets
    the ``min`` as candidate — resolve's edges are undirected, and
    backward cells keep first-seen-wins semantics).  Rows overflowing
    ``nc`` drop their largest-j edges (returned as the second element —
    connectivity via the smaller-j cells is what component-min resolve
    consumes first).
    """
    rb = np.tile(np.arange(n_bucket, dtype=np.int32)[:, None], (1, nc))
    fill = np.zeros(n_bucket, np.int32)
    dropped = 0
    for a, b in sorted(
        (max(int(a), int(b)), min(int(a), int(b))) for a, b in edges
    ):
        c = fill[a]
        if c >= nc:
            dropped += 1
            continue
        rb[a, c] = b
        fill[a] = c + 1
    return rb, dropped


# -- host twin of the wide band keys (the index re-probe key space) --------


def _fmix32_np(h: np.ndarray) -> np.ndarray:
    h = h.astype(np.uint32)
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> np.uint32(13))
    h = h * np.uint32(0xC2B2AE35)
    return h ^ (h >> np.uint32(16))


def band_keys_wide_host(
    sigs: np.ndarray, band_salt: np.ndarray
) -> np.ndarray:
    """``uint32[B, nb, 2]`` — numpy twin of ``ops.lsh.band_keys_wide``
    (same FNV-1a fold, same wide-lane constants, same rotated salt), so
    the tier's borderline ANN re-probe addresses the persistent index's
    EXACT posting key space without a device dispatch.  Parity with the
    device fn is pinned in ``tests/test_rerank_dispatch.py``."""
    sig = np.asarray(sigs, np.uint32)
    salt = np.asarray(band_salt, np.uint32)
    nb = salt.shape[0]
    B, P = sig.shape
    r = P // nb
    rows = sig.reshape(B, nb, r)
    lo = np.full((B, nb), FNV_OFFSET, np.uint32)
    hi = np.full((B, nb), np.uint32(0xCBF29CE4), np.uint32)
    for j in range(r):
        lo = (lo ^ rows[:, :, j]) * FNV_PRIME
        hi = (hi ^ rows[:, :, j]) * np.uint32(0x01000197)
    rot = (salt << np.uint32(13)) | (salt >> np.uint32(19))
    return np.stack(
        [_fmix32_np(lo ^ salt[None, :]), _fmix32_np(hi ^ rot[None, :])],
        axis=-1,
    )
