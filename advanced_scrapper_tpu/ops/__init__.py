from advanced_scrapper_tpu.ops.shingle import fmix32, shingle_hash
from advanced_scrapper_tpu.ops.minhash import (
    accumulate_block_signatures,
    combine_block_signatures,
    minhash_signatures,
)
from advanced_scrapper_tpu.ops.lsh import (
    band_keys,
    bucket_histogram,
    candidate_keys,
    duplicate_rep_bands,
    duplicate_reps,
    resolve_rep_bands,
    resolve_reps,
)
from advanced_scrapper_tpu.ops.exact import row_hash128

__all__ = [
    "fmix32",
    "shingle_hash",
    "minhash_signatures",
    "combine_block_signatures",
    "accumulate_block_signatures",
    "band_keys",
    "candidate_keys",
    "duplicate_reps",
    "duplicate_rep_bands",
    "resolve_reps",
    "resolve_rep_bands",
    "bucket_histogram",
    "row_hash128",
]
