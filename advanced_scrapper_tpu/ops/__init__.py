from advanced_scrapper_tpu.ops.shingle import fmix32, shingle_hash
from advanced_scrapper_tpu.ops.minhash import (
    minhash_signatures,
    combine_block_signatures,
)
from advanced_scrapper_tpu.ops.lsh import (
    band_keys,
    duplicate_reps,
    bucket_histogram,
)
from advanced_scrapper_tpu.ops.exact import row_hash128

__all__ = [
    "fmix32",
    "shingle_hash",
    "minhash_signatures",
    "combine_block_signatures",
    "band_keys",
    "duplicate_reps",
    "bucket_histogram",
    "row_hash128",
]
