"""Myers bit-parallel semi-global edit distance — the device fuzzy scorer.

SURVEY.md §7 names a "fuzzy ``partial_ratio``-equivalent scoring kernel"
as a kernels/ deliverable.  Exact rapidfuzz ``partial_ratio`` is a
max-over-windows LCS ratio — branchy and window-quadratic, a poor fit for
the MXU/VPU — but a *sound upper bound* on it is computable in one linear
scan with Myers' 1999 bit-parallel approximate-matching algorithm: the
minimum Levenshtein distance ``d`` between the pattern and ANY substring
of the text (semi-global: free start and end in the text), carried as two
32-bit bitvectors per pair, ~12 integer ops per text byte, ``vmap``-batched
over pairs and ``lax.scan``-ned over text positions.

Soundness (why pruning on the bound can never drop a true match): for the
best window ``w*`` (``|w*| ≤ m`` — rapidfuzz windows never exceed the
pattern length),

    partial_ratio = 100·(1 − d_indel(p, w*)/(m + |w*|))
                  ≤ 100·(1 − d_lev(p, w*)/(2m))      (d_indel ≥ d_lev, m+|w*| ≤ 2m)
                  ≤ 100·(1 − d_semi/(2m))            (w* is one substring)

so ``bound = 100·(1 − d_semi/(2m)) ≥ partial_ratio`` always; a pair with
``bound ≤ threshold`` is safe to prune before the exact host scorer
(``cpu/fuzz.py`` / ``native/fastmatch.cpp``).  Fuzz-tested against the
oracle.  The kernel applies only when ``len(text) ≥ len(pattern)`` and
``len(pattern) ≤ 32`` (one uint32 lane per pair); other pairs pass through
unpruned.

This complements the q-gram screen (``ops/match.py``): the screen is a
presence bitmap (no order information), this kernel is a true alignment
bound — together they remove almost all host-side quadratic scoring.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

MAX_PATTERN = 32  # one uint32 bitvector lane per pair


def build_pattern_masks(patterns: list[bytes]) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-pattern Myers match masks.

    Returns ``(masks uint32[N, 256], lens int32[N], ok bool[N])`` — ``ok``
    is False for empty or >32-byte patterns (callers must pass those
    through unpruned).
    """
    n = len(patterns)
    masks = np.zeros((n, 256), dtype=np.uint32)
    lens = np.zeros((n,), dtype=np.int32)
    ok = np.zeros((n,), dtype=bool)
    for i, p in enumerate(patterns):
        m = len(p)
        if m == 0 or m > MAX_PATTERN:
            continue
        lens[i] = m
        ok[i] = True
        for j, byte in enumerate(p):
            masks[i, byte] |= np.uint32(1) << np.uint32(j)
    return masks, lens, ok


def _semiglobal_core(masks, plens, text, tlens, block: int) -> jnp.ndarray:
    """Traceable body of :func:`semiglobal_dist` (per-pair text rows) —
    callable from inside an enclosing jit (the fused matcher screen step
    uses :func:`semiglobal_dist_shared`, the shared-text sibling)."""
    B, L = text.shape
    one = jnp.uint32(1)
    full = jnp.uint32(0xFFFFFFFF)
    O = MAX_PATTERN - 1
    nb = max(1, -(-L // block))
    # [B, nb, block+O]: overlapping tiles, sliced from the flat padded text
    # (the O-byte tail may span several following tiles when block < O)
    padded = jnp.pad(text, ((0, 0), (0, nb * block + O - L)))
    ext = jnp.stack(
        [padded[:, s : s + block + O] for s in range(0, nb * block, block)],
        axis=1,
    )
    starts = (jnp.arange(nb) * block).astype(jnp.int32)
    eff = jnp.clip(tlens[:, None] - starts[None, :], 0, block + O)  # [B, nb]

    # clamp: rows with plen 0 (inapplicable, caller discards) must not
    # shift by -1
    plens = jnp.maximum(plens.astype(jnp.int32), 1)
    high = (one << (plens.astype(jnp.uint32) - one))[:, None]  # [B, 1]
    p0 = jnp.broadcast_to(plens[:, None], (B, nb)).astype(jnp.int32)

    def step(carry, j):
        pv, mv, score, best = carry                      # each [B, nb]
        c = ext[:, :, j].astype(jnp.int32)               # [B, nb]
        eq = jnp.take_along_axis(masks, c, axis=1)       # [B, nb]
        xv = eq | mv
        xh = (((eq & pv) + pv) ^ pv) | eq
        ph = mv | ~(xh | pv)
        mh = pv & xh
        score2 = score + ((ph & high) != 0) - ((mh & high) != 0)
        # search variant: D[0][j] = 0 for every j (a match may start
        # anywhere), so the row-0 horizontal delta is 0 — shift WITHOUT
        # setting bit 0 (the global-distance variant would or-in 1 here)
        ph = ph << one
        mh = mh << one
        pv2 = mh | ~(xv | ph)
        mv2 = ph & xv
        live = j < eff
        pv = jnp.where(live, pv2, pv)
        mv = jnp.where(live, mv2, mv)
        score = jnp.where(live, score2, score)
        best = jnp.where(live, jnp.minimum(best, score), best)
        return (pv, mv, score, best), None

    init = (jnp.full((B, nb), full), jnp.zeros((B, nb), dtype=jnp.uint32), p0, p0)
    (_, _, _, best), _ = jax.lax.scan(step, init, jnp.arange(block + O))
    return best.min(axis=1)


@partial(jax.jit, static_argnames=("block",))
def semiglobal_dist(
    masks: jnp.ndarray,   # uint32[B, 256] per-pair pattern masks
    plens: jnp.ndarray,   # int32[B] pattern lengths (1..32)
    text: jnp.ndarray,    # uint8[B, L] per-pair text
    tlens: jnp.ndarray,   # int32[B] text lengths
    *,
    block: int = 512,
) -> jnp.ndarray:
    """int32[B]: min Levenshtein distance of pattern vs a text substring.

    The scan is *blocked*: the text splits into ``block``-byte tiles with a
    ``MAX_PATTERN-1``-byte overlap, all tiles advancing in lock-step as
    extra batch lanes — the sequential scan is ``block+31`` steps instead
    of ``L`` (Myers' carry chain is inherently sequential per tile, so the
    parallelism must come from the tile axis).  Every substring of length
    ≤ ``MAX_PATTERN`` lies inside one tile, so the result equals the true
    semi-global distance whenever the optimal substring is that short —
    and is an upper bound on it otherwise, which preserves the
    partial_ratio bound's soundness (rapidfuzz windows never exceed the
    pattern length).  Empty text (or ``tlens == 0``) gives ``plens``.
    """
    return _semiglobal_core(masks, plens, text, tlens, block)


def semiglobal_dist_shared(
    masks,   # uint32[K, 256] pattern masks (one per pattern, not per pair)
    plens,   # int32[K] pattern lengths (1..32)
    text,    # uint8[B, L] text rows
    tlens,   # int32[B] text lengths
    *,
    block: int = 512,
) -> jnp.ndarray:
    """int32[B, K]: :func:`semiglobal_dist` of EVERY pattern against
    EVERY text row, without materialising the ``B×K`` pair texts.

    The per-pair form gathers ``text[pair]`` into a ``[P, L]`` matrix —
    fine when pairs are sparse (the legacy host-selected survivor set),
    ruinous for the all-pairs fused screen step (``B·K·L`` bytes).  Here
    the pattern axis rides as an extra lane dimension over the SAME
    blocked text tiles: state is ``[K, B, nb]`` and each scan step reads
    one byte column ``c[B, nb]`` and looks it up in every pattern's mask
    (``masks[:, c]``), so memory is ``O(K·B·nb)`` state, never
    ``O(B·K·L)`` text.  Same blocked-tile semantics (and the same
    soundness argument) as :func:`semiglobal_dist`; traceable, so the
    fused matcher step calls it inside one jit.  Equality with the
    per-pair kernel is tested (``tests/test_match_dispatch.py``).
    """
    B, L = text.shape
    K = masks.shape[0]
    masks = jnp.asarray(masks)  # host constants must trace as device values
    one = jnp.uint32(1)
    full = jnp.uint32(0xFFFFFFFF)
    O = MAX_PATTERN - 1
    nb = max(1, -(-L // block))
    padded = jnp.pad(text, ((0, 0), (0, nb * block + O - L)))
    ext = jnp.stack(
        [padded[:, s : s + block + O] for s in range(0, nb * block, block)],
        axis=1,
    )                                                    # [B, nb, block+O]
    starts = (jnp.arange(nb) * block).astype(jnp.int32)
    eff = jnp.clip(tlens[:, None] - starts[None, :], 0, block + O)  # [B, nb]

    plens = jnp.maximum(plens.astype(jnp.int32), 1)
    high = (one << (plens.astype(jnp.uint32) - one))[:, None, None]  # [K,1,1]
    p0 = jnp.broadcast_to(plens[:, None, None], (K, B, nb)).astype(jnp.int32)

    def step(carry, j):
        pv, mv, score, best = carry                      # each [K, B, nb]
        c = ext[:, :, j].astype(jnp.int32)               # [B, nb]
        eq = masks[:, c]                                 # [K, B, nb]
        xv = eq | mv
        xh = (((eq & pv) + pv) ^ pv) | eq
        ph = mv | ~(xh | pv)
        mh = pv & xh
        score2 = score + ((ph & high) != 0) - ((mh & high) != 0)
        ph = ph << one                                   # search variant:
        mh = mh << one                                   # row 0 free (see
        pv2 = mh | ~(xv | ph)                            # _semiglobal_core)
        mv2 = ph & xv
        live = (j < eff)[None, :, :]
        pv = jnp.where(live, pv2, pv)
        mv = jnp.where(live, mv2, mv)
        score = jnp.where(live, score2, score)
        best = jnp.where(live, jnp.minimum(best, score), best)
        return (pv, mv, score, best), None

    init = (
        jnp.full((K, B, nb), full),
        jnp.zeros((K, B, nb), dtype=jnp.uint32),
        p0,
        p0,
    )
    (_, _, _, best), _ = jax.lax.scan(step, init, jnp.arange(block + O))
    return best.min(axis=2).T                            # [B, K]


def partial_ratio_bound(dist: np.ndarray, plens: np.ndarray) -> np.ndarray:
    """``100·(1 − d/(2m))`` — the sound upper bound on partial_ratio."""
    m = np.maximum(np.asarray(plens, dtype=np.float64), 1.0)
    return 100.0 * (1.0 - np.asarray(dist, dtype=np.float64) / (2.0 * m))


def prune_mask_tables(
    tables: tuple[np.ndarray, np.ndarray, np.ndarray],  # (masks, lens, ok)
    texts_tok: np.ndarray,   # uint8[P, L] gathered text per pair
    text_lens: np.ndarray,   # int32[P]
    pattern_ix: np.ndarray,  # int32[P] index into patterns per pair
    threshold: float,
) -> np.ndarray:
    """bool[P]: True where the pair can be PRUNED (bound ≤ threshold).

    ``tables`` is a prebuilt :func:`build_pattern_masks` result — build it
    once per entity index, not per slice.  Pairs whose pattern is
    empty/overlong, or whose text is NOT STRICTLY LONGER than the pattern,
    are never pruned: the bound's soundness argument needs ``|w| ≤ m``
    windows over a longer text, and rapidfuzz 3.x scores equal-length
    inputs in BOTH orientations (substrings of either side), which the
    one-direction semi-global bound does not cover.
    """
    masks, lens, ok = tables
    pattern_ix = np.asarray(pattern_ix, dtype=np.int32)
    applicable = ok[pattern_ix] & (
        np.asarray(text_lens, dtype=np.int32) > lens[pattern_ix]
    )
    if not applicable.any():
        return np.zeros(len(pattern_ix), dtype=bool)
    d = np.asarray(
        semiglobal_dist(
            jnp.asarray(masks[pattern_ix]),
            jnp.asarray(lens[pattern_ix]),
            jnp.asarray(texts_tok),
            jnp.asarray(text_lens, dtype=np.int32),
        )
    )
    bound = partial_ratio_bound(d, lens[pattern_ix])
    return applicable & (bound <= threshold)


def prune_mask(
    patterns: list[bytes],
    texts_tok: np.ndarray,
    text_lens: np.ndarray,
    pattern_ix: np.ndarray,
    threshold: float,
) -> np.ndarray:
    """One-shot convenience over :func:`prune_mask_tables` (builds the
    mask tables on every call — fine for tests/small inputs, use the
    tables form in loops)."""
    return prune_mask_tables(
        build_pattern_masks(patterns), texts_tok, text_lens, pattern_ix, threshold
    )
