"""Byte-shingle hashing on device.

Replaces the reference's CPU shingle-free string scans
(``match_keywords.py:165-180`` does O(names × article_len) rapidfuzz calls;
``yahoo_links_selenium.py:79`` hashes whole URLs inside pandas) with a
vectorised k-byte rolling FNV-1a + murmur3 finalisation over ``uint8[B, L]``
token blocks.  Everything is uint32: TPU vector lanes have native
wrap-around 32-bit integer multiply, so no 64-bit emulation is needed.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# numpy scalars, not jnp: creating a device array at import time would
# initialise the XLA backend before jax.distributed.initialize can run
# (multi-host entry points import this module first).
FNV_OFFSET = np.uint32(0x811C9DC5)
FNV_PRIME = np.uint32(0x01000193)
U32_MAX = np.uint32(0xFFFFFFFF)


def fmix32(h: jnp.ndarray) -> jnp.ndarray:
    """murmur3 32-bit finaliser — cheap avalanche for uint32 lanes."""
    h = h.astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def shingle_hash(
    tokens: jnp.ndarray, lengths: jnp.ndarray, k: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Hash every k-byte shingle of each row.

    Args:
      tokens: ``uint8[B, L]`` zero-padded byte rows.
      lengths: ``int32[B]`` valid byte counts.
      k: shingle width (static; the north star fixes k=5).

    Returns:
      ``(hashes uint32[B, L-k+1], valid bool[B, L-k+1])``.  ``valid[b, i]``
      iff shingle ``i`` lies fully inside the first ``lengths[b]`` bytes.

    The k-wide window is unrolled (k is tiny and static), producing k shifted
    elementwise ops XLA fuses into one pass — no gather, no dynamic shapes.
    """
    if tokens.ndim != 2:
        raise ValueError(f"tokens must be rank-2, got {tokens.shape}")
    L = tokens.shape[-1]
    if L < k:
        raise ValueError(f"block length {L} < shingle width {k}")
    t32 = tokens.astype(jnp.uint32)
    n = L - k + 1
    h = jnp.full(t32.shape[:-1] + (n,), FNV_OFFSET, dtype=jnp.uint32)
    for j in range(k):
        h = (h ^ t32[..., j : j + n]) * FNV_PRIME
    h = fmix32(h)
    pos = jnp.arange(n, dtype=jnp.int32)
    valid = pos < jnp.maximum(lengths - (k - 1), 0)[..., None]
    return h, valid


def gram_hash(tokens: jnp.ndarray, lengths: jnp.ndarray, q: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Alias of :func:`shingle_hash` for the q-gram match screen (q≠k)."""
    return shingle_hash(tokens, lengths, q)
