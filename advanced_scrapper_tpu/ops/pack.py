"""Tile packing for single-`device_put` H2D transfers.

The dedup hot path used to ship every tile as THREE host arrays —
``tokens uint8[rows, width]``, ``lengths int32[rows]``,
``owners int32[rows]`` — i.e. three ``jax.device_put`` calls per tile.
On transports where each put is a serialized round trip (the tunneled
dev chip; DESIGN.md §5) that is three round trips for one tile of work.

:func:`pack_tile` flattens the triple into ONE contiguous ``uint8``
buffer (tokens first, then the two int32 planes as little-endian byte
quadruples) so the whole tile crosses the host→device boundary in one
put; :func:`unpack_tile` re-slices it *inside* the jitted step — the
reconstruction is a reshape plus three shift-ors per int32 plane, noise
against the MinHash work that follows, and XLA fuses it into the kernel
prologue.

Layout (``rows``/``width`` are static per compiled step — the flat
buffer alone is ambiguous: ``rows·(width+8)`` collides across shapes)::

    [0, rows*width)              tokens, row-major uint8
    [rows*width, +4*rows)        lengths, int32 little-endian bytes
    [rows*width+4*rows, +4*rows) owners,  int32 little-endian bytes

Host-side packing is one preallocated buffer and three ``memcpy``-class
numpy assignments — no per-row Python work.
"""

from __future__ import annotations

import numpy as np

#: trailer bytes per row: lengths (4) + owners (4)
TRAILER_BYTES_PER_ROW = 8


def packed_nbytes(rows: int, width: int) -> int:
    """Size of a packed tile buffer in bytes."""
    return rows * (width + TRAILER_BYTES_PER_ROW)


def pack_tile(
    tok: np.ndarray, lens: np.ndarray, owners: np.ndarray
) -> np.ndarray:
    """``uint8[rows*(width+8)]`` single-buffer form of a ``(tokens,
    lengths, owners)`` tile (see module docstring for the layout)."""
    rows, width = tok.shape
    buf = np.empty(packed_nbytes(rows, width), np.uint8)
    buf[: rows * width] = tok.reshape(-1)
    off = rows * width
    buf[off : off + 4 * rows] = np.ascontiguousarray(
        lens, dtype="<i4"
    ).view(np.uint8)
    buf[off + 4 * rows :] = np.ascontiguousarray(
        owners, dtype="<i4"
    ).view(np.uint8)
    return buf


def unpack_tile(packed, rows: int, width: int):
    """Device-side inverse of :func:`pack_tile` — traceable under jit.

    Returns ``(tokens uint8[rows, width], lengths int32[rows],
    owners int32[rows])``.  The int32 planes are rebuilt from their
    little-endian bytes arithmetically (bitcast of a trailing uint8 axis
    is not portable across jax releases; four shift-ors are).
    """
    import jax.numpy as jnp

    tok = packed[: rows * width].reshape(rows, width)
    words = packed[rows * width :].astype(jnp.uint32).reshape(2, rows, 4)
    vals = (
        words[..., 0]
        | (words[..., 1] << 8)
        | (words[..., 2] << 16)
        | (words[..., 3] << 24)
    )
    lens = vals[0].astype(jnp.int32)
    owners = vals[1].astype(jnp.int32)
    return tok, lens, owners
