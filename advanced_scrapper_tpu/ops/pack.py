"""Tile packing for single-`device_put` H2D transfers.

The dedup hot path used to ship every tile as THREE host arrays —
``tokens uint8[rows, width]``, ``lengths int32[rows]``,
``owners int32[rows]`` — i.e. three ``jax.device_put`` calls per tile.
On transports where each put is a serialized round trip (the tunneled
dev chip; DESIGN.md §5) that is three round trips for one tile of work.

:func:`pack_tile_planes` flattens a ``(tokens, *int32 planes)`` tile
into ONE contiguous ``uint8`` buffer (tokens first, then each int32
plane as little-endian byte quadruples) so the whole tile crosses the
host→device boundary in one put; :func:`unpack_tile_planes` re-slices
it *inside* the jitted step — the reconstruction is a reshape plus
three shift-ors per int32 plane, noise against the kernel work that
follows, and XLA fuses it into the kernel prologue.  The plane count is
workload-shaped: the dedup tile carries two planes (lengths, owners —
:func:`pack_tile`/:func:`unpack_tile` keep that form's API), the
matcher screen tile five (combined length, text length, title length,
refine-eligibility flags, row→article owners).

Layout (``rows``/``width``/plane count are static per compiled step —
the flat buffer alone is ambiguous: ``rows·(width+4P)`` collides across
shapes)::

    [0, rows*width)                    tokens, row-major uint8
    [rows*width + 4*rows*k, +4*rows)   plane k, int32 little-endian bytes

Host-side packing is one preallocated buffer and ``1 + P``
``memcpy``-class numpy assignments — no per-row Python work.
"""

from __future__ import annotations

import numpy as np

#: trailer bytes per row of the 2-plane dedup tile: lengths (4) + owners (4)
TRAILER_BYTES_PER_ROW = 8


def packed_nbytes(rows: int, width: int, n_planes: int = 2) -> int:
    """Size of a packed tile buffer in bytes (``n_planes`` int32 planes)."""
    return rows * (width + 4 * n_planes)


def pack_tile_planes(tok: np.ndarray, *planes: np.ndarray) -> np.ndarray:
    """``uint8[rows*(width+4P)]`` single-buffer form of ``(tokens,
    *int32 planes)`` (see module docstring for the layout)."""
    rows, width = tok.shape
    buf = np.empty(packed_nbytes(rows, width, len(planes)), np.uint8)
    buf[: rows * width] = tok.reshape(-1)
    off = rows * width
    for plane in planes:
        buf[off : off + 4 * rows] = np.ascontiguousarray(
            plane, dtype="<i4"
        ).view(np.uint8)
        off += 4 * rows
    return buf


def unpack_tile_planes(packed, rows: int, width: int, n_planes: int):
    """Device-side inverse of :func:`pack_tile_planes` — traceable under
    jit.

    Returns ``(tokens uint8[rows, width], [plane int32[rows], …])``.
    The int32 planes are rebuilt from their little-endian bytes
    arithmetically (bitcast of a trailing uint8 axis is not portable
    across jax releases; four shift-ors are).
    """
    import jax.numpy as jnp

    tok = packed[: rows * width].reshape(rows, width)
    words = packed[rows * width :].astype(jnp.uint32).reshape(n_planes, rows, 4)
    vals = (
        words[..., 0]
        | (words[..., 1] << 8)
        | (words[..., 2] << 16)
        | (words[..., 3] << 24)
    )
    return tok, [vals[k].astype(jnp.int32) for k in range(n_planes)]


def pair_tile_nbytes(rows: int, sketch: int) -> int:
    """Size of a packed RERANK pair tile: two ``uint32[sketch]`` lanes per
    row (the pair's bottom-``sketch`` shingle sketches, 8·sketch bytes)
    plus one int32 fold-slot plane."""
    return packed_nbytes(rows, 8 * sketch, n_planes=1)


def pack_pair_tile(
    ska: np.ndarray, skb: np.ndarray, idx: np.ndarray
) -> np.ndarray:
    """``uint8[rows*(8*sketch+4)]`` single-buffer form of a rerank pair
    tile — the two sides' bottom-sketches side by side as the "token"
    block (little-endian uint32 bytes, side A then side B per row) and
    the pair's fold slot as the one int32 plane.  Same layout contract
    as :func:`pack_tile_planes`: rows/sketch are static per compiled
    step, so the whole tile crosses H2D as ONE ``device_put``."""
    rows, sketch = ska.shape
    tok = (
        np.ascontiguousarray(
            np.concatenate([ska, skb], axis=1), dtype="<u4"
        )
        .view(np.uint8)
        .reshape(rows, 8 * sketch)
    )
    return pack_tile_planes(tok, idx)


def unpack_pair_tile(packed, rows: int, sketch: int):
    """Device-side inverse of :func:`pack_pair_tile` — traceable under
    jit.

    Returns ``(ska uint32[rows, sketch], skb uint32[rows, sketch],
    idx int32[rows])``.  The uint32 lanes are rebuilt with the same
    four-shift-or recipe the int32 planes use (portable across jax
    releases, fused into the kernel prologue by XLA).
    """
    import jax.numpy as jnp

    tok, (idx,) = unpack_tile_planes(packed, rows, 8 * sketch, 1)
    words = tok.reshape(rows, 2 * sketch, 4).astype(jnp.uint32)
    vals = (
        words[..., 0]
        | (words[..., 1] << 8)
        | (words[..., 2] << 16)
        | (words[..., 3] << 24)
    )
    return vals[:, :sketch], vals[:, sketch:], idx


def pack_tile(
    tok: np.ndarray, lens: np.ndarray, owners: np.ndarray
) -> np.ndarray:
    """``uint8[rows*(width+8)]`` single-buffer form of the dedup
    ``(tokens, lengths, owners)`` tile."""
    return pack_tile_planes(tok, lens, owners)


def unpack_tile(packed, rows: int, width: int):
    """Device-side inverse of :func:`pack_tile` — traceable under jit.

    Returns ``(tokens uint8[rows, width], lengths int32[rows],
    owners int32[rows])``.
    """
    tok, (lens, owners) = unpack_tile_planes(packed, rows, width, 2)
    return tok, lens, owners
