"""Exact (128-bit) row hashing for byte-identical dedup paths.

Replaces the hash table inside pandas ``drop_duplicates``
(``yahoo_links_selenium.py:79,174``) for the URL exact-dedup path.  Each row
gets four independent 32-bit linear hashes ``h = fmix32(Σ c_i·x_i ⊕
mix(len))`` — a random-coefficient dot product, which is one fused
multiply-reduce on the VPU.  Zero padding contributes nothing to the sum, and
the length is mixed in so ``"ab"`` ≠ ``"ab\\x00"``.

A 128-bit hash makes collisions astronomically unlikely (~2⁻¹²⁸ per pair),
but "astronomically unlikely" is not "byte-identical": the host path
(``pipeline/dedup.py``) verifies actual string equality within hash-equal
groups before dropping a row, so output CSVs are guaranteed byte-identical
to the pandas path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from advanced_scrapper_tpu.ops.shingle import fmix32

_N_LANES = 4


class ExactHasher:
    """Seeded 128-bit row hasher; coefficient tables are cached per row length."""

    def __init__(self, seed: int = 0xA5C3):
        self._seed = seed
        self._stream = np.zeros((_N_LANES, 0), dtype=np.uint32)

    def _coef(self, L: int) -> np.ndarray:
        # One infinite per-lane stream, materialised lazily: coef(L) is always
        # a prefix of coef(L'), so the same bytes hash identically regardless
        # of which padded bucket length a batch happened to use.
        if self._stream.shape[1] < L:
            cols = []
            for lane in range(_N_LANES):
                gen = np.random.RandomState((self._seed * 7919 + lane) % (1 << 31))
                cols.append(
                    gen.randint(0, 1 << 32, size=L, dtype=np.uint64).astype(np.uint32)
                )
            self._stream = np.stack(cols)
        return self._stream[:, :L]

    def __call__(self, tokens: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
        """``uint8[B, L] -> uint32[B, 4]`` (a 128-bit hash in 4 lanes)."""
        return _row_hash_impl(tokens, lengths, jnp.asarray(self._coef(tokens.shape[-1])))


@jax.jit
def _row_hash_impl(
    tokens: jnp.ndarray, lengths: jnp.ndarray, coef: jnp.ndarray
) -> jnp.ndarray:
    t = tokens.astype(jnp.uint32)
    # [B, 1, L] * [1, 4, L] summed over L; uint32 accumulate wraps mod 2^32.
    dots = (t[:, None, :] * coef[None, :, :]).sum(axis=-1, dtype=jnp.uint32)
    lmix = fmix32(lengths.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
    lane_salt = jnp.arange(_N_LANES, dtype=jnp.uint32) * jnp.uint32(0x85EBCA77)
    return fmix32(dots ^ lmix[:, None] ^ lane_salt[None, :])


row_hash128 = ExactHasher()
