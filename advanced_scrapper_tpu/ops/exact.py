"""Exact (128-bit) row hashing for byte-identical dedup paths.

Replaces the hash table inside pandas ``drop_duplicates``
(``yahoo_links_selenium.py:79,174``) for the URL exact-dedup path.  Each row
gets four independent 32-bit linear hashes ``h = fmix32(Σ c_i·x_i ⊕
mix(len))`` — a random-coefficient dot product, which is one fused
multiply-reduce on the VPU.  Zero padding contributes nothing to the sum, and
the length is mixed in so ``"ab"`` ≠ ``"ab\\x00"``.

A 128-bit hash makes collisions astronomically unlikely (~2⁻¹²⁸ per pair),
but "astronomically unlikely" is not "byte-identical": the host path
(``pipeline/dedup.py``) verifies actual string equality within hash-equal
groups before dropping a row, so output CSVs are guaranteed byte-identical
to the pandas path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from advanced_scrapper_tpu.ops.shingle import fmix32

_N_LANES = 4

#: Hard ceiling for blockwise-hashed documents (4 MiB — far beyond any
#: article body).  Not a correctness limit: the coefficient stream costs
#: ~16 bytes per byte of the longest document, so one pathological blob
#: must fail loudly rather than OOM the host.
MAX_DOC_LEN = 1 << 22


class ExactHasher:
    """Seeded 128-bit row hasher; coefficient tables are cached per row length."""

    def __init__(self, seed: int = 0xA5C3):
        self._seed = seed
        self._stream = np.zeros((_N_LANES, 0), dtype=np.uint32)

    def _coef(self, L: int) -> np.ndarray:
        # One infinite per-lane stream, materialised lazily: coef(L) is always
        # a prefix of coef(L'), so the same bytes hash identically regardless
        # of which padded bucket length a batch happened to use.
        if self._stream.shape[1] < L:
            cols = []
            for lane in range(_N_LANES):
                gen = np.random.RandomState((self._seed * 7919 + lane) % (1 << 31))
                cols.append(
                    gen.randint(0, 1 << 32, size=L, dtype=np.uint64).astype(np.uint32)
                )
            self._stream = np.stack(cols)
        return self._stream[:, :L]

    def __call__(self, tokens: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
        """``uint8[B, L] -> uint32[B, 4]`` (a 128-bit hash in 4 lanes)."""
        return _row_hash_impl(tokens, lengths, jnp.asarray(self._coef(tokens.shape[-1])))

    def hash_docs(
        self, raw: list[bytes], *, block_len: int = 4096
    ) -> np.ndarray:
        """``uint32[n, 4]`` — the same 128-bit hash at ANY document length.

        The row hash is a linear form ``fmix32(Σ c_i·x_i ⊕ mix(len))``, so a
        long document's sum splits exactly across fixed-shape blocks: block
        p's partial dot uses the coefficient slice at offset ``p·block_len``
        (the per-lane stream is prefix-consistent, so short docs hash
        identically to the single-block path), partials segment-sum per doc
        (uint32 wrap = mod-2³² addition, associative), and the length mix is
        applied once at the end.  This removes the old ``max_len`` ceiling:
        article bodies of any size get exact-hashed blockwise, the same
        combine trick the MinHash path uses (VERDICT r2 item 5).
        """
        from advanced_scrapper_tpu.core.tokenizer import bucket_len, encode_blocks

        n = len(raw)
        if n == 0:
            return np.zeros((0, _N_LANES), np.uint32)
        longest = max(len(r) for r in raw)
        if longest > MAX_DOC_LEN:
            raise ValueError(
                f"item of {longest} bytes exceeds MAX_DOC_LEN {MAX_DOC_LEN}; "
                "the linear hash needs one coefficient per byte (~16 B/byte "
                "host + device), so an unbounded item would silently become "
                "an allocation storm — reject it loudly instead"
            )
        tok, _block_lens, owners = encode_blocks(raw, block_len, overlap=0)
        true_lens = np.fromiter((len(r) for r in raw), np.int64, count=n)
        # block position within its doc: owners is ascending, so the first
        # block of doc d sits at searchsorted(owners, d).
        block_pos = (
            np.arange(tok.shape[0]) - np.searchsorted(owners, owners)
        ).astype(np.int32)
        # bucket the position axis so the coef tensor's shape is O(log) stable
        n_pos = bucket_len(int(block_pos.max()) + 1, min_bucket=8)
        coef = self._coef(n_pos * block_len)  # [4, n_pos*block_len]
        coef_blocks = np.ascontiguousarray(
            coef.reshape(_N_LANES, n_pos, block_len).transpose(1, 0, 2)
        )
        # Pad the block axis to a bucket so compiled shapes stay O(log N);
        # padded rows point at doc slot n (a scratch row sliced off below).
        n_blocks = tok.shape[0]
        nb_bucket = bucket_len(n_blocks, min_bucket=64)
        if nb_bucket != n_blocks:
            pad = nb_bucket - n_blocks
            tok = np.concatenate([tok, np.zeros((pad, block_len), np.uint8)])
            owners = np.concatenate([owners, np.full((pad,), n, np.int32)])
            block_pos = np.concatenate([block_pos, np.zeros((pad,), np.int32)])
        n_doc_bucket = bucket_len(n + 1, min_bucket=64)
        lens_pad = np.zeros((n_doc_bucket,), np.int32)
        lens_pad[:n] = true_lens
        out = _block_hash_impl(
            tok,
            jnp.asarray(block_pos),
            jnp.asarray(owners),
            jnp.asarray(lens_pad),
            jnp.asarray(coef_blocks),
            num_docs=n_doc_bucket,
        )
        return np.asarray(out)[:n]


@partial(jax.jit, static_argnames=("num_docs",))
def _block_hash_impl(
    tokens: jnp.ndarray,
    block_pos: jnp.ndarray,
    owners: jnp.ndarray,
    doc_lengths: jnp.ndarray,
    coef_blocks: jnp.ndarray,
    *,
    num_docs: int,
) -> jnp.ndarray:
    """Blockwise 128-bit hash: per-block partial dots (coefficients gathered
    by block position) segment-summed per document, then length-mixed."""
    t = tokens.astype(jnp.uint32)
    c = jnp.take(coef_blocks, block_pos, axis=0)  # [N, 4, BL]
    dots = (t[:, None, :] * c).sum(axis=-1, dtype=jnp.uint32)  # [N, 4]
    total = jax.ops.segment_sum(dots, owners, num_segments=num_docs)
    lmix = fmix32(doc_lengths.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
    lane_salt = jnp.arange(_N_LANES, dtype=jnp.uint32) * jnp.uint32(0x85EBCA77)
    return fmix32(total.astype(jnp.uint32) ^ lmix[:, None] ^ lane_salt[None, :])


@jax.jit
def _row_hash_impl(
    tokens: jnp.ndarray, lengths: jnp.ndarray, coef: jnp.ndarray
) -> jnp.ndarray:
    t = tokens.astype(jnp.uint32)
    # [B, 1, L] * [1, 4, L] summed over L; uint32 accumulate wraps mod 2^32.
    dots = (t[:, None, :] * coef[None, :, :]).sum(axis=-1, dtype=jnp.uint32)
    lmix = fmix32(lengths.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
    lane_salt = jnp.arange(_N_LANES, dtype=jnp.uint32) * jnp.uint32(0x85EBCA77)
    return fmix32(dots ^ lmix[:, None] ^ lane_salt[None, :])


row_hash128 = ExactHasher()
