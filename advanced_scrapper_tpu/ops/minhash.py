"""MinHash signature kernel.

The TPU successor of datasketch-style CPU MinHash (the oracle in
``cpu/oracle.py`` reproduces datasketch exactly; see ``core/hashing.py`` for
why the device uses a 32-bit multiply-add family instead of 61-bit Mersenne
arithmetic).  Configuration fixed by the north star (BASELINE.json): k=5 byte
shingles, 128 permutations.

Shape/memory strategy: the naive formulation materialises
``uint32[B, S, 128]`` (shingles × permutations).  We instead scan over
shingle-position chunks, keeping a running per-permutation minimum — peak
intermediate is ``[B, chunk, 128]`` and XLA fuses the multiply-add into the
min-reduction.  Long articles are handled *blockwise* upstream
(``core.tokenizer.encode_blocks``; k-1 byte overlap) and block signatures are
combined here with a segment-min — the same algebra lets sequence-parallel
shards combine partial signatures with ``lax.pmin`` over the mesh's ``seq``
axis (``parallel/sharded.py``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from advanced_scrapper_tpu.core.hashing import MinHashParams
from advanced_scrapper_tpu.ops.shingle import U32_MAX, shingle_hash


def scan_min_signature(
    h: jnp.ndarray,
    valid: jnp.ndarray,
    a: jnp.ndarray,
    b: jnp.ndarray,
    chunk: int,
) -> jnp.ndarray:
    """Per-permutation minimum over shingle hashes, scanned in chunks.

    ``h/valid`` are ``[B, S]``; peak intermediate is ``[B, chunk, P]``
    (XLA fuses the multiply-add into the min-reduce).  Shared by the
    single-device kernel and the sequence-parallel shard kernel.
    """
    B, S = h.shape
    P = a.shape[0]
    # Pad shingle axis to a chunk multiple, transpose chunks to the scan axis.
    n_chunks = -(-S // chunk)
    pad = n_chunks * chunk - S
    h = jnp.pad(h, ((0, 0), (0, pad)))
    valid = jnp.pad(valid, ((0, 0), (0, pad)))
    h_t = h.reshape(B, n_chunks, chunk).transpose(1, 0, 2)
    v_t = valid.reshape(B, n_chunks, chunk).transpose(1, 0, 2)

    def body(sig, xs):
        hc, vc = xs  # uint32[B, chunk], bool[B, chunk]
        ph = a[None, None, :] * hc[:, :, None] + b[None, None, :]
        ph = jnp.where(vc[:, :, None], ph, U32_MAX)
        return jnp.minimum(sig, ph.min(axis=1)), None

    init = jnp.full((B, P), U32_MAX, dtype=jnp.uint32)
    sig, _ = jax.lax.scan(body, init, (h_t, v_t))
    return sig


@partial(jax.jit, static_argnames=("k", "chunk"))
def _signatures_impl(
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    a: jnp.ndarray,
    b: jnp.ndarray,
    *,
    k: int,
    chunk: int,
) -> jnp.ndarray:
    h, valid = shingle_hash(tokens, lengths, k)
    return scan_min_signature(h, valid, a, b, chunk)


def minhash_signatures(
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    params: MinHashParams,
    *,
    chunk: int = 128,
) -> jnp.ndarray:
    """Compute ``uint32[B, num_perm]`` MinHash signatures.

    Rows with fewer than k valid bytes yield all-``U32_MAX`` signatures;
    callers must mask them out of LSH (``lsh.duplicate_reps(valid=...)``).

    ``chunk=128`` is the measured-best scan granularity on v5e (2026-07
    sweep: ~845k articles/s full-step at [32768, 1024] vs ~715k at 512).
    The multiply-add per (shingle, permutation) is irreducible for the
    dense formulation and the MXU cannot help (min-reduce is not a
    matmul), but the kernel is NOT at VPU ceiling: the measured 778k
    articles/s works out to ~5% of the nominal v5e 32-bit VPU rate
    (~17% counting int32-multiply decomposition into 16-bit passes) and
    ~1.3% of HBM bandwidth — roofline arithmetic in DESIGN.md
    "Roofline", MFU field in bench JSON.  Headroom exists in principle;
    at 15.5× the 50k/s target it is not the binding constraint.  See
    ``ops/oph.py`` for the measured alternative that trades multiplies
    for a sort.

    ``ASTPU_MINHASH_BACKEND=pallas`` swaps in the fused Pallas kernel
    (``ops/pallas_minhash.py``) — bit-identical output, measured slower on
    v5e, kept as the hand-written reference for the op.
    """
    from advanced_scrapper_tpu.ops.pallas_minhash import (
        minhash_signatures_pallas,
        pallas_enabled,
    )

    if pallas_enabled() and params.num_perm == 128:
        return minhash_signatures_pallas(tokens, lengths, params)
    return _signatures_impl(
        tokens,
        lengths,
        jnp.asarray(params.a32),
        jnp.asarray(params.b32),
        k=params.shingle_k,
        chunk=chunk,
    )


def resolve_signature_fn(backend: str):
    """Single dispatch point for the signature backend.

    ``scan`` — the dense kernel (measured fastest on v5e); ``pallas`` —
    the fused hand-written kernel; ``oph`` — one-permutation hashing
    (densified; whole-document rows only — block/shard-split callers must
    use ``ops.oph.oph_raw_signatures`` and densify after the min-combine).
    Unknown names raise instead of silently running scan.
    """
    if backend == "scan":
        return minhash_signatures
    if backend == "pallas":
        from advanced_scrapper_tpu.ops.pallas_minhash import (
            minhash_signatures_pallas,
        )

        return minhash_signatures_pallas
    if backend == "oph":
        from advanced_scrapper_tpu.ops.oph import oph_signatures

        return oph_signatures
    raise ValueError(f"unknown signature backend {backend!r}; use scan|pallas|oph")


@partial(jax.jit, static_argnames=("num_articles",))
def combine_block_signatures(
    block_sigs: jnp.ndarray, owners: jnp.ndarray, *, num_articles: int
) -> jnp.ndarray:
    """Per-article signature = elementwise min over its blocks' signatures.

    MinHash is a min-reduction over the shingle set, and the blockwise split
    (with k-1 overlap) preserves the shingle set, so segment-min over blocks
    is *exact*, not an approximation.  TPU analogue of the reference's
    chunked streaming (``match_keywords.py:227-230``).
    """
    return jax.ops.segment_min(
        block_sigs, owners, num_segments=num_articles, indices_are_sorted=False
    )


def make_fused_tile_step(params: MinHashParams, backend: str):
    """Build the SINGLE-dispatch per-tile step of the packed dedup path:
    ``(running, packed) -> running'`` — unpack the one-buffer tile
    (``ops.pack``), compute block signatures, segment-min them per
    article, and fold into the DONATED running accumulator, all inside
    one jitted call.

    The legacy path pays two dispatches per tile (``block_fn`` then
    :func:`accumulate_block_signatures`); on a tunneled transport each
    dispatch is a control-channel round trip, so halving the count is a
    direct latency win (SEDD's per-batch launch-minimisation argument —
    PAPERS.md).  Donating ``running`` extends the donation already on
    the legacy accumulate: the device updates the accumulator in place,
    no per-tile ``[num_articles, P]`` allocation.

    ``backend == "oph"`` uses the RAW OPH form (empty bins ``U32_MAX``)
    so the min-combine stays exact; callers densify once after the last
    tile (``ops/oph.py`` on why that order is load-bearing).  The
    params arrays are closure-captured (constant-folded into the
    compiled step), so cache the returned callable per (params,
    backend) — ``pipeline.dedup.NearDupEngine`` holds one per engine.

    SENTINEL CONTRACT: this builder returns the raw ``jax.jit`` object
    (exposing ``_cache_size``) — the pipeline layer wraps it in the
    recompile sentinel (``obs.devprof.instrument_jit``, counting every
    jit-cache miss on ``astpu_jit_compiles_total{kernel=
    "dedup_fused_tile"}``; ops may not import obs — layering).  Wrapping
    the step in anything that hides ``_cache_size`` silently blinds the
    sentinel AND the prewarm-set gate tests.
    """
    if backend == "oph":
        from advanced_scrapper_tpu.ops.oph import oph_raw_signatures

        block_fn = oph_raw_signatures
    else:
        block_fn = resolve_signature_fn(backend)

    from advanced_scrapper_tpu.ops.pack import unpack_tile

    @partial(
        jax.jit,
        static_argnames=("rows", "width", "num_articles"),
        donate_argnums=(0,),
    )
    def fused_tile_step(
        running: jnp.ndarray,
        packed: jnp.ndarray,
        *,
        rows: int,
        width: int,
        num_articles: int,
    ) -> jnp.ndarray:
        tok, lens, owners = unpack_tile(packed, rows, width)
        sigs = block_fn(tok, lens, params)
        part = jax.ops.segment_min(
            sigs, owners, num_segments=num_articles, indices_are_sorted=False
        )
        return jnp.minimum(running, part)

    return fused_tile_step


@partial(jax.jit, static_argnames=("num_articles",), donate_argnums=(0,))
def accumulate_block_signatures(
    running: jnp.ndarray,
    block_sigs: jnp.ndarray,
    owners: jnp.ndarray,
    *,
    num_articles: int,
) -> jnp.ndarray:
    """One streamed step of the block→article combine: fold a fixed-shape
    batch of block signatures into the running ``uint32[num_articles, P]``
    minimum.  Min is associative/commutative, so folding batch-by-batch is
    bit-identical to one whole-corpus :func:`combine_block_signatures` —
    but each step dispatches asynchronously (and donates ``running``'s
    buffer), so host encode, H2D, and device compute overlap instead of
    serialising on a per-batch device sync (the round-2 ragged-regime
    bottleneck).  Padding rows carry all-``U32_MAX`` signatures (the min
    identity): their owner index is irrelevant.
    """
    part = jax.ops.segment_min(
        block_sigs, owners, num_segments=num_articles, indices_are_sorted=False
    )
    return jnp.minimum(running, part)
