"""CPU MinHash/LSH oracle — algorithm-identical to ``datasketch``.

``datasketch`` is the recall baseline named in BASELINE.json but is not
installable in this environment, so this module re-implements its exact
algorithm (verified against the published datasketch behaviour):

- base hash: first 4 bytes of SHA1, little-endian (``sha1_hash32``);
- permutations: ``h_i(x) = ((a_i·x + b_i) mod (2^61 - 1)) & 0xFFFFFFFF``
  with ``a_i ∈ [1, p)``, ``b_i ∈ [0, p)`` drawn from
  ``np.random.RandomState(seed)`` in datasketch's order (``core.hashing``);
- signature: elementwise min over the shingle set, initialised to 2^32-1;
- LSH: hash-table buckets keyed by band tuples (16 bands × 8 rows).

This oracle defines ground truth for the ≥0.95 near-dup recall metric and
is deliberately simple, slow and obviously-correct numpy.
"""

from __future__ import annotations

import hashlib
import struct
from collections import defaultdict
from typing import Iterable, Sequence

import numpy as np

from advanced_scrapper_tpu.core.hashing import MAX_HASH, MERSENNE_PRIME, MinHashParams


def sha1_hash32(data: bytes) -> int:
    """datasketch's default hash: low 4 bytes of SHA1, little-endian."""
    return struct.unpack("<I", hashlib.sha1(data).digest()[:4])[0]


def shingle_set(text: str | bytes, k: int) -> set[bytes]:
    raw = text.encode("utf-8", errors="replace") if isinstance(text, str) else text
    if len(raw) < k:
        return set()
    return {raw[i : i + k] for i in range(len(raw) - k + 1)}


def jaccard(a: set, b: set) -> float:
    if not a and not b:
        return 1.0
    if not a or not b:
        return 0.0
    return len(a & b) / len(a | b)


def oracle_signature(text: str | bytes, params: MinHashParams) -> np.ndarray:
    """uint64[num_perm] signature, exactly as datasketch.MinHash.update()."""
    hv = np.full(params.num_perm, int(MAX_HASH), dtype=np.uint64)
    for sh in shingle_set(text, params.shingle_k):
        x = np.uint64(sha1_hash32(sh))
        phv = ((params.a61 * x + params.b61) % MERSENNE_PRIME) & MAX_HASH
        hv = np.minimum(hv, phv)
    return hv


def oracle_signatures(
    texts: Sequence[str | bytes], params: MinHashParams
) -> np.ndarray:
    return np.stack([oracle_signature(t, params) for t in texts])


def band_tuples(sig: np.ndarray, params: MinHashParams) -> list[tuple]:
    r = params.rows_per_band
    return [tuple(sig[b * r : (b + 1) * r].tolist()) for b in range(params.num_bands)]


def oracle_candidate_pairs(
    sigs: np.ndarray, params: MinHashParams
) -> set[tuple[int, int]]:
    """All (i < j) pairs sharing at least one LSH band bucket."""
    pairs: set[tuple[int, int]] = set()
    for b in range(params.num_bands):
        buckets: dict[tuple, list[int]] = defaultdict(list)
        r = params.rows_per_band
        for i in range(sigs.shape[0]):
            buckets[tuple(sigs[i, b * r : (b + 1) * r].tolist())].append(i)
        for members in buckets.values():
            if len(members) > 1:
                members.sort()
                for x in range(len(members)):
                    for y in range(x + 1, len(members)):
                        pairs.add((members[x], members[y]))
    return pairs


def estimated_jaccard(sig_a: np.ndarray, sig_b: np.ndarray) -> float:
    return float(np.mean(sig_a == sig_b))


def oracle_dedup_reps(
    texts: Sequence[str | bytes],
    params: MinHashParams,
    threshold: float,
) -> np.ndarray:
    """First-seen-wins union-find dedup, the CPU twin of
    ``ops.lsh.duplicate_reps`` + ``resolve_reps``."""
    sigs = oracle_signatures(texts, params)
    n = len(texts)
    parent = np.arange(n)

    def find(i: int) -> int:
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    for i, j in sorted(oracle_candidate_pairs(sigs, params)):
        if estimated_jaccard(sigs[i], sigs[j]) >= threshold:
            ri, rj = find(i), find(j)
            if ri != rj:
                lo, hi = min(ri, rj), max(ri, rj)
                parent[hi] = lo
    return np.array([find(i) for i in range(n)], dtype=np.int32)


def oracle_near_dup_pairs(
    texts: Sequence[str | bytes],
    params: MinHashParams,
    threshold: float,
) -> set[tuple[int, int]]:
    """Candidate pairs whose estimated Jaccard clears ``threshold`` —
    the pair set the recall metric is computed against."""
    sigs = oracle_signatures(texts, params)
    return {
        (i, j)
        for i, j in oracle_candidate_pairs(sigs, params)
        if estimated_jaccard(sigs[i], sigs[j]) >= threshold
    }
