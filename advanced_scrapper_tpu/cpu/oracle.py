"""CPU MinHash/LSH oracle — algorithm-identical to ``datasketch``.

``datasketch`` is the recall baseline named in BASELINE.json but is not
installable in this environment, so this module re-implements its exact
algorithm (verified against the published datasketch behaviour):

- base hash: first 4 bytes of SHA1, little-endian (``sha1_hash32``);
- permutations: ``h_i(x) = ((a_i·x + b_i) mod (2^61 - 1)) & 0xFFFFFFFF``
  with ``a_i ∈ [1, p)``, ``b_i ∈ [0, p)`` drawn from
  ``np.random.RandomState(seed)`` in datasketch's order (``core.hashing``);
- signature: elementwise min over the shingle set, initialised to 2^32-1;
- LSH: hash-table buckets keyed by band tuples (16 bands × 8 rows).

This oracle defines ground truth for the ≥0.95 near-dup recall metric and
is deliberately simple, slow and obviously-correct numpy.
"""

from __future__ import annotations

import hashlib
import struct
from collections import defaultdict
from typing import Iterable, Sequence

import numpy as np

from advanced_scrapper_tpu.core.hashing import MAX_HASH, MERSENNE_PRIME, MinHashParams


def sha1_hash32(data: bytes) -> int:
    """datasketch's default hash: low 4 bytes of SHA1, little-endian."""
    return struct.unpack("<I", hashlib.sha1(data).digest()[:4])[0]


def shingle_set(text: str | bytes, k: int) -> set[bytes]:
    raw = text.encode("utf-8", errors="replace") if isinstance(text, str) else text
    if len(raw) < k:
        return set()
    return {raw[i : i + k] for i in range(len(raw) - k + 1)}


def jaccard(a: set, b: set) -> float:
    if not a and not b:
        return 1.0
    if not a or not b:
        return 0.0
    return len(a & b) / len(a | b)


def oracle_signature(text: str | bytes, params: MinHashParams) -> np.ndarray:
    """uint64[num_perm] signature, exactly as datasketch.MinHash.update()."""
    hv = np.full(params.num_perm, int(MAX_HASH), dtype=np.uint64)
    for sh in shingle_set(text, params.shingle_k):
        x = np.uint64(sha1_hash32(sh))
        phv = ((params.a61 * x + params.b61) % MERSENNE_PRIME) & MAX_HASH
        hv = np.minimum(hv, phv)
    return hv


def oracle_signatures(
    texts: Sequence[str | bytes], params: MinHashParams
) -> np.ndarray:
    return np.stack([oracle_signature(t, params) for t in texts])


def oracle_signatures_fast(
    texts: Sequence[str | bytes],
    params: MinHashParams,
    *,
    chunk: int = 8192,
    _sha_cache: dict | None = None,
) -> np.ndarray:
    """Vectorised, bit-identical twin of :func:`oracle_signatures`.

    Same algorithm (sha1_hash32 base hash, 61-bit Mersenne permutations,
    elementwise min) but the per-shingle Python loop collapses to chunked
    numpy over ``[chunk, num_perm]`` tiles, and sha1 values are memoised
    across documents — planted near-dup corpora share most shingles with
    their base docs, so the certification corpus in
    ``tests/test_recall_vs_oracle.py`` gets oracle truth in seconds instead
    of minutes.  Equality with the slow oracle is CI-tested.
    """
    cache: dict[bytes, int] = {} if _sha_cache is None else _sha_cache
    out = np.empty((len(texts), params.num_perm), dtype=np.uint64)
    a = params.a61[None, :]
    b = params.b61[None, :]
    for t_i, text in enumerate(texts):
        shingles = shingle_set(text, params.shingle_k)
        hv = np.full(params.num_perm, int(MAX_HASH), dtype=np.uint64)
        if shingles:
            xs = np.fromiter(
                (
                    cache[sh] if sh in cache else cache.setdefault(sh, sha1_hash32(sh))
                    for sh in shingles
                ),
                dtype=np.uint64,
                count=len(shingles),
            )
            for start in range(0, len(xs), chunk):
                x = xs[start : start + chunk, None]
                phv = ((a * x + b) % MERSENNE_PRIME) & MAX_HASH
                hv = np.minimum(hv, phv.min(axis=0))
        out[t_i] = hv
    return out


def band_tuples(sig: np.ndarray, params: MinHashParams) -> list[tuple]:
    r = params.rows_per_band
    return [tuple(sig[b * r : (b + 1) * r].tolist()) for b in range(params.num_bands)]


def oracle_candidate_pairs(
    sigs: np.ndarray, params: MinHashParams
) -> set[tuple[int, int]]:
    """All (i < j) pairs sharing at least one LSH band bucket."""
    pairs: set[tuple[int, int]] = set()
    for b in range(params.num_bands):
        buckets: dict[tuple, list[int]] = defaultdict(list)
        r = params.rows_per_band
        for i in range(sigs.shape[0]):
            buckets[tuple(sigs[i, b * r : (b + 1) * r].tolist())].append(i)
        for members in buckets.values():
            if len(members) > 1:
                members.sort()
                for x in range(len(members)):
                    for y in range(x + 1, len(members)):
                        pairs.add((members[x], members[y]))
    return pairs


def estimated_jaccard(sig_a: np.ndarray, sig_b: np.ndarray) -> float:
    return float(np.mean(sig_a == sig_b))


def oracle_dedup_reps(
    texts: Sequence[str | bytes],
    params: MinHashParams,
    threshold: float,
) -> np.ndarray:
    """First-seen-wins union-find dedup, the CPU twin of
    ``ops.lsh.duplicate_reps`` + ``resolve_reps``."""
    sigs = oracle_signatures(texts, params)
    n = len(texts)
    parent = np.arange(n)

    def find(i: int) -> int:
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    for i, j in sorted(oracle_candidate_pairs(sigs, params)):
        if estimated_jaccard(sigs[i], sigs[j]) >= threshold:
            ri, rj = find(i), find(j)
            if ri != rj:
                lo, hi = min(ri, rj), max(ri, rj)
                parent[hi] = lo
    return np.array([find(i) for i in range(n)], dtype=np.int32)


def oracle_near_dup_pairs(
    texts: Sequence[str | bytes],
    params: MinHashParams,
    threshold: float,
    *,
    fast: bool = False,
) -> set[tuple[int, int]]:
    """Candidate pairs whose estimated Jaccard clears ``threshold`` —
    the pair set the recall metric is computed against."""
    sigs = (oracle_signatures_fast if fast else oracle_signatures)(texts, params)
    return {
        (i, j)
        for i, j in oracle_candidate_pairs(sigs, params)
        if estimated_jaccard(sigs[i], sigs[j]) >= threshold
    }


def mutate_to_jaccard(
    rng: np.random.RandomState, text: bytes, target_j: float
) -> bytes:
    """Mutant whose k-shingle Jaccard with ``text`` lands near ``target_j``.

    A contiguous substring of fraction ``f = (1-j)/(1+j)`` is replaced with
    random bytes: the surviving shingles ≈ (1-f)·S shared out of ≈ (1+f)·S
    union, giving J ≈ (1-f)/(1+f) — invertible, so the certification corpus
    can PLANT pairs across the LSH sensitivity knee instead of only the
    easy J→1 regime (the round-2 recall-test weakness)."""
    f = (1.0 - target_j) / (1.0 + target_j)
    span = max(1, int(len(text) * f))
    pos = rng.randint(0, max(1, len(text) - span))
    b = bytearray(text)
    b[pos : pos + span] = rng.randint(32, 127, size=span, dtype=np.uint8).tobytes()
    return bytes(b)


def build_certification_corpus(
    rng: np.random.RandomState,
    n_bases: int,
    *,
    min_len: int = 100,
    max_len: int = 20000,
    n_long: int = 12,
    long_len: int = 100_000,
    knee_frac: float = 0.4,
) -> list[bytes]:
    """Recall-certification corpus: ragged lengths (log-uniform
    ``min_len..max_len`` plus ``n_long`` docs at ``long_len`` forcing the
    blockwise segment-min combine), each base planted with two mutants —
    a ``knee_frac`` share targeted across the Jaccard knee (0.62..0.80,
    where LSH candidacy is genuinely probabilistic) and the rest in the
    easy high-similarity regime (0.85..0.97) — shuffled together with an
    equal count of unrelated docs."""
    lens = np.exp(
        rng.uniform(np.log(min_len), np.log(max_len), size=n_bases)
    ).astype(np.int64)
    lens[:n_long] = long_len
    texts: list[bytes] = []
    for i in range(n_bases):
        base = rng.randint(32, 127, size=int(lens[i]), dtype=np.uint8).tobytes()
        texts.append(base)
        for _ in range(2):
            if rng.rand() < knee_frac:
                tj = rng.uniform(0.62, 0.80)
            else:
                tj = rng.uniform(0.85, 0.97)
            texts.append(mutate_to_jaccard(rng, base, tj))
        texts.append(
            rng.randint(32, 127, size=int(lens[rng.randint(n_bases)]), dtype=np.uint8).tobytes()
        )
    order = rng.permutation(len(texts))
    return [texts[i] for i in order]


def oracle_reps(
    texts: Sequence[str | bytes],
    params: MinHashParams,
    threshold: float,
    *,
    fast: bool = False,
    pairs: set[tuple[int, int]] | None = None,
) -> np.ndarray:
    """Cluster representatives from the ORACLE's own pair set — i.e. what
    "datasketch plus union-find" would keep (first-seen wins: every
    cluster's rep is its smallest index).  This is the comparator for the
    engine's precision: both sides threshold the same 128-lane estimator
    and close transitively, so the engine's merged-pair precision is
    certified against this clustering's, not against an unreachable 1.0.

    ``pairs`` may carry a precomputed ``oracle_near_dup_pairs`` result —
    the pair set is the expensive part, and recall metrics already
    computed it for the same corpus.
    """
    parent = np.arange(len(texts))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = int(parent[x])
        return x

    if pairs is None:
        pairs = oracle_near_dup_pairs(texts, params, threshold, fast=fast)
    for i, j in pairs:
        ri, rj = find(i), find(j)
        if ri != rj:
            # union by min index so reps are first-seen, like the engine
            lo, hi = (ri, rj) if ri < rj else (rj, ri)
            parent[hi] = lo
    return np.array([find(i) for i in range(len(texts))])


def measured_recall(
    texts: Sequence[str | bytes],
    reps: np.ndarray,
    params: MinHashParams,
    threshold: float,
    *,
    pairs: set[tuple[int, int]] | None = None,
) -> tuple[float, int]:
    """(recall, n_oracle_pairs): fraction of datasketch-semantics near-dup
    pairs the engine clustered together (``reps`` from
    ``NearDupEngine.dedup_reps``).  The north-star bar is ≥0.95
    (BASELINE.json).  ``pairs`` reuses a precomputed oracle pair set
    (callers that also build ``oracle_reps`` share one computation)."""
    if pairs is None:
        pairs = oracle_near_dup_pairs(texts, params, threshold, fast=True)
    if not pairs:
        return 1.0, 0
    hit = sum(1 for i, j in pairs if reps[i] == reps[j])
    return hit / len(pairs), len(pairs)


def measured_precision(
    texts: Sequence[str | bytes],
    reps: np.ndarray,
    shingle_k: int,
    threshold: float,
    *,
    edge_slack: float = 0.10,
) -> tuple[float, int, int]:
    """``(precision, n_engine_pairs, n_unchained)`` over the pairs the
    ENGINE merged (same rep), judged by TRUE shingle-set Jaccard.

    ``precision`` counts merged pairs with true J ≥ ``threshold``.  It is
    NOT expected to be 1.0: both the engine and datasketch threshold an
    *estimator* (128-lane agreement), so edges slightly below threshold
    can verify, and transitive closure then merges mutant-mutant pairs
    whose direct J is lower still — identical behaviour to datasketch
    plus union-find.

    The hard certification is ``n_unchained``: every member of a cluster
    must be REACHABLE from its peers through edges of true
    J ≥ ``threshold − edge_slack`` (edges the estimator can plausibly
    accept; at J = 0.60 a false accept is <1% per edge).  A member only
    reachable through weaker edges is a genuine false merge — the bar is
    ZERO.
    """
    clusters: dict[int, list[int]] = defaultdict(list)
    for i, r in enumerate(reps):
        clusters[int(r)].append(i)

    edge_bar = threshold - edge_slack
    n_pairs = good = unchained = 0
    for members in clusters.values():
        m = len(members)
        if m < 2:
            continue
        # shingle sets scoped per cluster: cross-cluster pairs are never
        # compared, so peak memory is one cluster's worth, not the corpus'
        sets = [shingle_set(texts[i], shingle_k) for i in members]
        jmat = np.ones((m, m))
        for a in range(m):
            for b in range(a + 1, m):
                jmat[a, b] = jmat[b, a] = jaccard(sets[a], sets[b])
        n_pairs += m * (m - 1) // 2
        good += int(np.count_nonzero(np.triu(jmat >= threshold, k=1)))
        # members outside the LARGEST strong-edge component are the wrongly
        # attached ones (seeding from an arbitrary member would overcount
        # whenever the weak outlier happened to be the seed)
        strong = jmat >= edge_bar
        unvisited = np.ones(m, bool)
        biggest = 0
        while unvisited.any():
            seed = int(np.flatnonzero(unvisited)[0])
            seen = np.zeros(m, bool)
            seen[seed] = True
            frontier = [seed]
            while frontier:
                nxt = np.flatnonzero(strong[frontier].any(axis=0) & ~seen)
                seen[nxt] = True
                frontier = nxt.tolist()
            biggest = max(biggest, int(seen.sum()))
            unvisited &= ~seen
        unchained += m - biggest
    return (good / n_pairs if n_pairs else 1.0), n_pairs, unchained
