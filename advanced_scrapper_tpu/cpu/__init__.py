from advanced_scrapper_tpu.cpu.oracle import (
    sha1_hash32,
    oracle_signature,
    oracle_signatures,
    oracle_candidate_pairs,
    oracle_dedup_reps,
    shingle_set,
    jaccard,
)
from advanced_scrapper_tpu.cpu.fuzz import ratio, partial_ratio

__all__ = [
    "sha1_hash32",
    "oracle_signature",
    "oracle_signatures",
    "oracle_candidate_pairs",
    "oracle_dedup_reps",
    "shingle_set",
    "jaccard",
    "ratio",
    "partial_ratio",
]
