"""ctypes loader for the C++ host kernels (``native/fastmatch.cpp``).

The library is compiled on demand with g++ (once per source change — the
.so is cached next to the source with an mtime check) and falls back to the
pure-Python oracle in ``cpu/fuzz.py`` when no compiler is available, so the
framework stays importable everywhere.  Use :func:`partial_ratio` /
:func:`ratio`; :data:`BACKEND` reports which implementation is live.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native", "fastmatch.cpp")
_LIB = os.path.join(os.path.dirname(_SRC), "libfastmatch.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
BACKEND = "unloaded"


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _LIB],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, BACKEND
    with _lock:
        if BACKEND != "unloaded":
            return _lib
        needs_build = (not os.path.exists(_LIB)) or (
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        )
        if needs_build and not _build():
            BACKEND = "python"
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            BACKEND = "python"
            return None
        for name in ("fm_ratio", "fm_partial_ratio", "fm_ratio_u32",
                     "fm_partial_ratio_u32"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_double
            fn.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ]
        for name in ("fm_partial_ratio_cutoff", "fm_partial_ratio_cutoff_u32"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_double
            fn.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                ctypes.c_double,
            ]
        _lib = lib
        BACKEND = "native"
        return lib


def _enc(s: str | bytes) -> bytes:
    return s if isinstance(s, bytes) else s.encode("utf-8", "replace")


def _call(
    byte_fn, u32_fn, py_fn, s1: str | bytes, s2: str | bytes, *extra
) -> float:
    """Dispatch: bytes/ASCII → byte kernel; non-ASCII str → UTF-32 kernel
    (rapidfuzz scores code points, not bytes — byte-level scoring diverges
    on curly quotes/accents/CJK); no compiler → pure-Python oracle.
    ``extra`` args (e.g. a score cutoff) forward to every backend, so the
    routing rules live here once for all entry points."""
    lib = _load()
    if lib is None:
        from advanced_scrapper_tpu.cpu import fuzz

        a = s1.decode("utf-8", "replace") if isinstance(s1, bytes) else s1
        b = s2.decode("utf-8", "replace") if isinstance(s2, bytes) else s2
        return py_fn(fuzz, a, b, *extra)
    if isinstance(s1, str) and isinstance(s2, str) and not (
        s1.isascii() and s2.isascii()
    ):
        # surrogatepass: scraped text may carry lone surrogates; rapidfuzz
        # scores raw ord() values, and strict utf-32 would raise on them
        a32 = s1.encode("utf-32-le", "surrogatepass")
        b32 = s2.encode("utf-32-le", "surrogatepass")
        return getattr(lib, u32_fn)(a32, len(s1), b32, len(s2), *extra)
    a, b = _enc(s1), _enc(s2)
    return getattr(lib, byte_fn)(a, len(a), b, len(b), *extra)


def ratio(s1: str | bytes, s2: str | bytes) -> float:
    return _call("fm_ratio", "fm_ratio_u32", lambda f, a, b: f.ratio(a, b), s1, s2)


def partial_ratio(s1: str | bytes, s2: str | bytes) -> float:
    return _call(
        "fm_partial_ratio", "fm_partial_ratio_u32",
        lambda f, a, b: f.partial_ratio(a, b), s1, s2,
    )


def partial_ratio_cutoff(s1: str | bytes, s2: str | bytes, cutoff: float) -> float:
    """rapidfuzz ``score_cutoff`` semantics: the exact partial_ratio when it
    reaches ``cutoff``, else 0.0.  The native kernel skips windows whose
    sliding character-multiset bound cannot reach the cutoff — at the
    matcher's >95 verify this is ~10-50× the full scan on non-matching
    (name, article) pairs, with fuzzed parity vs
    ``rapidfuzz.fuzz.partial_ratio(score_cutoff=...)``."""

    def py_fallback(f, a, b, c):
        score = f.partial_ratio(a, b)
        return score if score >= c else 0.0

    return _call(
        "fm_partial_ratio_cutoff", "fm_partial_ratio_cutoff_u32",
        py_fallback, s1, s2, cutoff,
    )
