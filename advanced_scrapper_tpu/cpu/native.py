"""ctypes loader for the C++ host kernels (``native/fastmatch.cpp``).

The library is compiled on demand with g++ (once per source change — the
.so is cached next to the source with an mtime check) and falls back to the
pure-Python oracle in ``cpu/fuzz.py`` when no compiler is available, so the
framework stays importable everywhere.  Use :func:`partial_ratio` /
:func:`ratio`; :data:`BACKEND` reports which implementation is live.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native", "fastmatch.cpp")
_LIB = os.path.join(os.path.dirname(_SRC), "libfastmatch.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
BACKEND = "unloaded"


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _LIB],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, BACKEND
    if BACKEND != "unloaded":  # hot path: no lock once resolved (set-once)
        return _lib
    with _lock:
        if BACKEND != "unloaded":
            return _lib
        needs_build = (not os.path.exists(_LIB)) or (
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        )
        if needs_build and not _build():
            BACKEND = "python"
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            BACKEND = "python"
            return None
        for name in ("fm_ratio", "fm_partial_ratio", "fm_ratio_u32",
                     "fm_partial_ratio_u32"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_double
            fn.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ]
        for name in ("fm_partial_ratio_cutoff", "fm_partial_ratio_cutoff_u32"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_double
            fn.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                ctypes.c_double,
            ]
        select = lib.fm_partial_ratio_cutoff_select
        select.restype = None
        select.argtypes = [
            ctypes.c_char_p, ctypes.c_int,           # haystack
            ctypes.c_char_p, ctypes.c_void_p,        # needle arena + offsets
            ctypes.c_void_p,                         # lengths
            ctypes.c_void_p, ctypes.c_int,           # select rows + count
            ctypes.c_double, ctypes.c_void_p,        # cutoff + out scores
        ]
        # multi-pattern matcher core (guarded: a stale .so predating it
        # just disables the automaton fast path, never the whole backend)
        if hasattr(lib, "fm_ac_build"):
            lib.fm_ac_build.restype = ctypes.c_void_p
            lib.fm_ac_build.argtypes = [
                ctypes.c_char_p, ctypes.c_void_p, ctypes.c_long,
            ]
            lib.fm_ac_scan.restype = ctypes.c_long
            lib.fm_ac_scan.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long,
            ]
            lib.fm_ac_destroy.restype = None
            lib.fm_ac_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        BACKEND = "native"
        return lib


def _enc(s: str | bytes) -> bytes:
    return s if isinstance(s, bytes) else s.encode("utf-8", "replace")


def _call(
    byte_fn, u32_fn, py_fn, s1: str | bytes, s2: str | bytes, *extra
) -> float:
    """Dispatch: bytes/ASCII → byte kernel; non-ASCII str → UTF-32 kernel
    (rapidfuzz scores code points, not bytes — byte-level scoring diverges
    on curly quotes/accents/CJK); no compiler → pure-Python oracle.
    ``extra`` args (e.g. a score cutoff) forward to every backend, so the
    routing rules live here once for all entry points."""
    lib = _load()
    if lib is None:
        from advanced_scrapper_tpu.cpu import fuzz

        a = s1.decode("utf-8", "replace") if isinstance(s1, bytes) else s1
        b = s2.decode("utf-8", "replace") if isinstance(s2, bytes) else s2
        return py_fn(fuzz, a, b, *extra)
    if isinstance(s1, str) and isinstance(s2, str) and not (
        s1.isascii() and s2.isascii()
    ):
        # surrogatepass: scraped text may carry lone surrogates; rapidfuzz
        # scores raw ord() values, and strict utf-32 would raise on them
        a32 = s1.encode("utf-32-le", "surrogatepass")
        b32 = s2.encode("utf-32-le", "surrogatepass")
        return getattr(lib, u32_fn)(a32, len(s1), b32, len(s2), *extra)
    a, b = _enc(s1), _enc(s2)
    return getattr(lib, byte_fn)(a, len(a), b, len(b), *extra)


def ratio(s1: str | bytes, s2: str | bytes) -> float:
    return _call("fm_ratio", "fm_ratio_u32", lambda f, a, b: f.ratio(a, b), s1, s2)


def partial_ratio(s1: str | bytes, s2: str | bytes) -> float:
    return _call(
        "fm_partial_ratio", "fm_partial_ratio_u32",
        lambda f, a, b: f.partial_ratio(a, b), s1, s2,
    )


def partial_ratio_cutoff(s1: str | bytes, s2: str | bytes, cutoff: float) -> float:
    """rapidfuzz ``score_cutoff`` semantics: the exact partial_ratio when it
    reaches ``cutoff``, else 0.0.  The native kernel skips windows whose
    sliding character-multiset bound cannot reach the cutoff — at the
    matcher's >95 verify this is ~10-50× the full scan on non-matching
    (name, article) pairs, with fuzzed parity vs
    ``rapidfuzz.fuzz.partial_ratio(score_cutoff=...)``."""

    def py_fallback(f, a, b, c):
        score = f.partial_ratio(a, b)
        return score if score >= c else 0.0

    return _call(
        "fm_partial_ratio_cutoff", "fm_partial_ratio_cutoff_u32",
        py_fallback, s1, s2, cutoff,
    )


def partial_ratio_cutoff_many(
    haystack: str | bytes, needles: list[str | bytes], cutoff: float
):
    """``float64[len(needles)]`` of :func:`partial_ratio_cutoff` scores of
    one haystack against many needles in one native call.  One-shot
    convenience over :class:`CutoffArena` (which repeated callers with a
    fixed needle set should hold directly) so the ASCII/fallback routing
    rules live in exactly one place."""
    return CutoffArena(needles).scores(haystack, range(len(needles)), cutoff)


class CutoffArena:
    """Persistent packed-needle arena for repeated cutoff scoring.

    Built once per fixed name set (an entity index); each call ships only
    the selected row ids to the native kernel — no per-article re-encoding
    or arena rebuild (the per-call overhead :func:`partial_ratio_cutoff_many`
    still pays).  Non-ASCII names, non-ASCII haystacks, and no-compiler
    hosts transparently take the per-pair route with identical scores.
    """

    def __init__(self, names: list[str | bytes]):
        import numpy as np

        self.names = list(names)
        self._per_pair_rows = {
            i for i, nd in enumerate(self.names)
            if isinstance(nd, str) and not nd.isascii()
        }
        enc = [
            b"" if i in self._per_pair_rows else _enc(nd)
            for i, nd in enumerate(self.names)
        ]
        self._lengths = np.array([len(e) for e in enc], dtype=np.int32)
        self._offsets = np.zeros(len(enc), dtype=np.int64)
        if len(enc) > 1:
            self._offsets[1:] = np.cumsum(self._lengths[:-1], dtype=np.int64)
        self._arena = b"".join(enc)

    def scores(self, haystack: str | bytes, rows, cutoff: float):
        """``float64[len(rows)]`` — ``partial_ratio_cutoff(haystack,
        names[r], cutoff)`` for each selected row ``r``."""
        import numpy as np

        rows = np.asarray(rows, dtype=np.int32)
        out = np.zeros(len(rows), dtype=np.float64)
        if len(rows) == 0:
            return out
        lib = _load()
        hay_ascii = isinstance(haystack, bytes) or haystack.isascii()
        if lib is None or not hay_ascii:
            for i, r in enumerate(rows):
                out[i] = partial_ratio_cutoff(haystack, self.names[r], cutoff)
            return out
        if self._per_pair_rows:
            batch = np.array(
                [r for r in rows if int(r) not in self._per_pair_rows],
                dtype=np.int32,
            )
        else:
            batch = rows
        if len(batch):
            hay = _enc(haystack)
            scores = np.zeros(len(batch), dtype=np.float64)
            lib.fm_partial_ratio_cutoff_select(
                hay, len(hay), self._arena, self._offsets.ctypes.data,
                self._lengths.ctypes.data, batch.ctypes.data, len(batch),
                cutoff, scores.ctypes.data,
            )
            if len(batch) == len(rows):
                return scores
            by_row = dict(zip(batch.tolist(), scores.tolist()))
            for i, r in enumerate(rows.tolist()):
                if r in by_row:
                    out[i] = by_row[r]
        for i, r in enumerate(rows.tolist()):
            if r in self._per_pair_rows:
                out[i] = partial_ratio_cutoff(haystack, self.names[r], cutoff)
        return out


class MultiPattern:
    """Multi-pattern exact matcher (native Aho-Corasick over bytes).

    Built once per fixed pattern set; :meth:`scan` enumerates EVERY
    occurrence of every pattern in one pass over the text — the successor
    of the matcher's per-name ``re.finditer`` loops, where each ALL-CAPS
    entity name re-scanned the whole article.  Byte-level: callers gate on
    ASCII (byte offsets == char offsets there) and apply word-boundary /
    non-overlap semantics themselves.

    ``available`` is False without a compiler (or on a stale .so predating
    ``fm_ac_build``); callers then keep their per-name scan path.
    """

    def __init__(self, patterns: list[bytes]):
        import numpy as np

        self.patterns = [bytes(p) for p in patterns]
        self._handle = None
        lib = _load()
        if lib is None or not hasattr(lib, "fm_ac_build"):
            return
        lens = np.fromiter(map(len, self.patterns), np.int64, len(self.patterns))
        offsets = np.zeros((len(self.patterns) + 1,), dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        blob = b"".join(self.patterns)
        handle = lib.fm_ac_build(blob, offsets.ctypes.data, len(self.patterns))
        if handle:
            self._lib = lib
            self._handle = ctypes.c_void_p(handle)

    @property
    def available(self) -> bool:
        return self._handle is not None

    def scan(self, text: bytes):
        """``(ids int32[k], starts int64[k])`` — every (pattern, start)
        occurrence, in end-position order (per-pattern starts ascending)."""
        import numpy as np

        if self._handle is None:
            raise RuntimeError("MultiPattern built without a native backend")
        cap = 256
        while True:
            ids = np.zeros((cap,), dtype=np.int32)
            starts = np.zeros((cap,), dtype=np.int64)
            n = self._lib.fm_ac_scan(
                self._handle, text, len(text),
                ids.ctypes.data, starts.ctypes.data, cap,
            )
            if n <= cap:
                return ids[:n], starts[:n]
            cap = int(n)  # exact total reported: one retry always suffices

    def __del__(self):
        h, self._handle = self._handle, None
        if h is not None:
            try:
                self._lib.fm_ac_destroy(h)
            except Exception:
                pass  # interpreter teardown: the OS reclaims it anyway
