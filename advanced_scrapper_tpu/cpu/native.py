"""ctypes loader for the C++ host kernels (``native/fastmatch.cpp``).

The library is compiled on demand with g++ (once per source change — the
.so is cached next to the source with an mtime check) and falls back to the
pure-Python oracle in ``cpu/fuzz.py`` when no compiler is available, so the
framework stays importable everywhere.  Use :func:`partial_ratio` /
:func:`ratio`; :data:`BACKEND` reports which implementation is live.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native", "fastmatch.cpp")
_LIB = os.path.join(os.path.dirname(_SRC), "libfastmatch.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
BACKEND = "unloaded"


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _LIB],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, BACKEND
    with _lock:
        if BACKEND != "unloaded":
            return _lib
        needs_build = (not os.path.exists(_LIB)) or (
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        )
        if needs_build and not _build():
            BACKEND = "python"
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            BACKEND = "python"
            return None
        lib.fm_ratio.restype = ctypes.c_double
        lib.fm_ratio.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.fm_partial_ratio.restype = ctypes.c_double
        lib.fm_partial_ratio.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ]
        _lib = lib
        BACKEND = "native"
        return lib


def _enc(s: str | bytes) -> bytes:
    return s if isinstance(s, bytes) else s.encode("utf-8", "replace")


def ratio(s1: str | bytes, s2: str | bytes) -> float:
    lib = _load()
    a, b = _enc(s1), _enc(s2)
    if lib is not None:
        return lib.fm_ratio(a, len(a), b, len(b))
    from advanced_scrapper_tpu.cpu import fuzz

    return fuzz.ratio(a.decode("utf-8", "replace"), b.decode("utf-8", "replace"))


def partial_ratio(s1: str | bytes, s2: str | bytes) -> float:
    lib = _load()
    a, b = _enc(s1), _enc(s2)
    if lib is not None:
        return lib.fm_partial_ratio(a, len(a), b, len(b))
    from advanced_scrapper_tpu.cpu import fuzz

    return fuzz.partial_ratio(a.decode("utf-8", "replace"), b.decode("utf-8", "replace"))
