"""ctypes loader for the zero-copy exact-dedup kernel
(``native/exactdedup.cpp``).

Unlike the other native helpers this one includes ``Python.h`` (it reads
str/bytes buffers in place, so the host never flattens the corpus), which
means it needs the CPython dev headers to build and the GIL to run — it is
loaded through :class:`ctypes.PyDLL` and treated as strictly optional: any
build/load failure just routes ``ExactDedup`` to the blob tier
(``cpu.hostbatch.exact_keep_first_native``) or the grouping fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
import threading

import numpy as np

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(__file__)), "native", "exactdedup.cpp"
)
_LIB = os.path.join(os.path.dirname(_SRC), "libexactdedup.so")

_lock = threading.Lock()
_lib: ctypes.PyDLL | None = None
_backend = "unloaded"


def _build() -> bool:
    include = sysconfig.get_paths().get("include")
    if not include or not os.path.exists(os.path.join(include, "Python.h")):
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", f"-I{include}", _SRC,
             "-o", _LIB],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load() -> ctypes.PyDLL | None:
    global _lib, _backend
    if _backend != "unloaded":
        return _lib
    with _lock:
        if _backend != "unloaded":
            return _lib
        needs_build = (not os.path.exists(_LIB)) or (
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        )
        if needs_build and not _build():
            _backend = "python"
            return None
        try:
            # PyDLL: calls run WITH the GIL held — the kernel walks live
            # Python objects, so releasing it (plain CDLL) would race the
            # interpreter
            lib = ctypes.PyDLL(_LIB)
            lib.ed_keep_first_list.restype = ctypes.c_long
            lib.ed_keep_first_list.argtypes = [
                ctypes.py_object, ctypes.c_void_p,
            ]
        except (OSError, AttributeError):
            _backend = "python"
            return None
        _lib = lib
        _backend = "native"
        return lib


def exactdedup_backend() -> str:
    """'native' or 'python' (after first use)."""
    _load()
    return _backend


def keep_first_list(items) -> np.ndarray | None:
    """``uint8[n]`` first-seen keep mask straight over a list of str or
    bytes, or None when this tier can't serve it (no kernel, non-list
    input, mixed str/bytes, or items UTF-8 can't view losslessly)."""
    lib = _load()
    if lib is None or not isinstance(items, list):
        return None
    keep = np.zeros((len(items),), dtype=np.uint8)
    rc = lib.ed_keep_first_list(items, keep.ctypes.data)
    if rc < 0:
        return None
    return keep
