"""ctypes loader for the zero-copy exact-dedup kernel
(``native/exactdedup.cpp``).

Unlike the other native helpers this one includes ``Python.h`` (it reads
str/bytes buffers in place, so the host never flattens the corpus), which
means it needs the CPython dev headers to build and the GIL to run — it is
loaded through :class:`ctypes.PyDLL` and treated as strictly optional: any
build/load failure just routes ``ExactDedup`` to the blob tier
(``cpu.hostbatch.exact_keep_first_native``) or the grouping fallback.
"""

from __future__ import annotations

import ctypes
import os
import sysconfig
import threading

import numpy as np

from advanced_scrapper_tpu.cpu.nativebuild import build_or_find, find_fresh

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(__file__)), "native", "exactdedup.cpp"
)
_LIB = os.path.join(os.path.dirname(_SRC), "libexactdedup.so")

_lock = threading.Lock()
_lib: ctypes.PyDLL | None = None
_backend = "unloaded"
_reason = ""  # why the native tier is unavailable ("" when it is)


def _load() -> ctypes.PyDLL | None:
    global _lib, _backend, _reason
    if _backend != "unloaded":
        return _lib
    with _lock:
        if _backend != "unloaded":
            return _lib
        # a prebuilt fresh .so loads WITHOUT the CPython dev headers —
        # they are a compile-time prerequisite only (a deploy box that
        # ships the binary must not fall back just because it could not
        # have built it)
        lib_path = find_fresh(_SRC, _LIB)
        if lib_path is None:
            include = sysconfig.get_paths().get("include")
            if not include or not os.path.exists(
                os.path.join(include, "Python.h")
            ):
                _backend, _reason = "python", "CPython dev headers not found"
                return None
            # build beside the source, falling back to a temp dir when
            # the repo is unwritable; the failure reason is kept for
            # reporting (bench exposes it — a silent fallback cost
            # BENCH_r05 12× on the exact regime)
            lib_path, why = build_or_find(_SRC, _LIB, (f"-I{include}",))
            if lib_path is None:
                _backend, _reason = "python", why
                return None
        try:
            # PyDLL: calls run WITH the GIL held — the kernel walks live
            # Python objects, so releasing it (plain CDLL) would race the
            # interpreter
            lib = ctypes.PyDLL(lib_path)
            lib.ed_keep_first_list.restype = ctypes.c_long
            lib.ed_keep_first_list.argtypes = [
                ctypes.py_object, ctypes.c_void_p,
            ]
        except (OSError, AttributeError) as e:
            _backend, _reason = "python", f"load failed: {e}"
            return None
        _lib = lib
        _backend = "native"
        return lib


def exactdedup_backend() -> str:
    """'native' or 'python' (after first use)."""
    _load()
    return _backend


def backend_reason() -> str:
    """Why the native tier is unavailable — "" when it is live."""
    _load()
    return _reason


def keep_first_list(items) -> np.ndarray | None:
    """``uint8[n]`` first-seen keep mask straight over a list of str or
    bytes, or None when this tier can't serve it (no kernel, non-list
    input, mixed str/bytes, or items UTF-8 can't view losslessly)."""
    lib = _load()
    if lib is None or not isinstance(items, list):
        return None
    keep = np.zeros((len(items),), dtype=np.uint8)
    rc = lib.ed_keep_first_list(items, keep.ctypes.data)
    if rc < 0:
        return None
    return keep
