"""Reference implementation of rapidfuzz's ``fuzz.ratio`` / ``fuzz.partial_ratio``.

``match_keywords.py:175-176`` gates the fuzzy entity-match path on
``rapidfuzz.fuzz.partial_ratio(text, name) > 95``.  This module implements
the same semantics dependency-free (the production deployment cannot assume
rapidfuzz), and is CI-fuzzed for exact score parity against the *installed*
rapidfuzz 3.x (``tests/test_rapidfuzz_parity.py``):

- ``ratio(s1, s2)``: normalised indel similarity,
  ``100 * (1 - dist / (len1 + len2))`` where ``dist`` is the
  insertion/deletion-only edit distance ``len1 + len2 - 2*LCS``.
- ``partial_ratio(s1, s2)``: max ``ratio`` of the shorter string against
  the sliding windows of its length across the longer, including the
  partial windows overhanging either end.  Two rapidfuzz-3.x rules beyond
  the naive slide (both verified against rapidfuzz 3.14.5 and its shipped
  ``fuzz_py.py``):
  * an empty needle scores **0.0** against non-empty text (only
    empty-vs-empty is 100.0) — ``fuzz_py.partial_ratio_alignment:314``;
  * **equal-length** inputs are scanned in BOTH directions (substrings of
    each side against the other) and the max taken —
    ``fuzz_py.partial_ratio_alignment:327-332``.  This is where naive
    sliding diverges by 1-7 points.

This pure-Python version is the oracle for tests and small inputs; the C++
twin (bit-parallel Hyyrö LCS, ``native/fastmatch.cpp``) is the production
verifier behind the TPU q-gram screen.
"""

from __future__ import annotations

from functools import lru_cache


def _lcs_len(a: str, b: str) -> int:
    """Classic O(|a|·|b|) LCS-length DP (row-rolling)."""
    if not a or not b:
        return 0
    if len(a) < len(b):
        a, b = b, a
    prev = [0] * (len(b) + 1)
    for ca in a:
        cur = [0] * (len(b) + 1)
        for j, cb in enumerate(b, 1):
            cur[j] = prev[j - 1] + 1 if ca == cb else max(prev[j], cur[j - 1])
        prev = cur
    return prev[-1]


def indel_distance(s1: str, s2: str) -> int:
    return len(s1) + len(s2) - 2 * _lcs_len(s1, s2)


def ratio(s1: str, s2: str) -> float:
    total = len(s1) + len(s2)
    if total == 0:
        return 100.0
    return 100.0 * (1.0 - indel_distance(s1, s2) / total)


def _scan_windows(needle: str, haystack: str) -> float:
    """Max ratio of ``needle`` vs the length-|needle| sliding windows of
    ``haystack`` (clipped at both edges)."""
    m, n = len(needle), len(haystack)
    best = 0.0
    for start in range(-(m - 1), n):
        lo, hi = max(0, start), min(n, start + m)
        if hi <= lo:
            continue
        sc = ratio(needle, haystack[lo:hi])
        if sc > best:
            best = sc
            if best >= 100.0:
                break
    return best


def partial_ratio(s1: str, s2: str) -> float:
    if not s1 and not s2:
        return 100.0
    shorter, longer = (s1, s2) if len(s1) <= len(s2) else (s2, s1)
    m, n = len(shorter), len(longer)
    if m == 0:
        return 0.0  # empty needle vs non-empty text (rapidfuzz 3.x)
    best = _scan_windows(shorter, longer)
    if best < 100.0 and m == n:
        # equal lengths: rapidfuzz scans both orientations and takes the max
        best = max(best, _scan_windows(longer, shorter))
    return best


@lru_cache(maxsize=65536)
def partial_ratio_cached(s1: str, s2: str) -> float:
    return partial_ratio(s1, s2)
