"""Reference implementation of rapidfuzz's ``fuzz.ratio`` / ``fuzz.partial_ratio``.

``match_keywords.py:175-176`` gates the fuzzy entity-match path on
``rapidfuzz.fuzz.partial_ratio(text, name) > 95``.  rapidfuzz is not
installable here, so this module is the semantic reference:

- ``ratio(s1, s2)``: normalised indel similarity,
  ``100 * (1 - dist / (len1 + len2))`` where ``dist`` is the
  insertion/deletion-only edit distance ``len1 + len2 - 2*LCS``.
- ``partial_ratio(s1, s2)``: the shorter string slides over the longer; the
  score is the max ``ratio`` over windows of the shorter string's length,
  including the partial windows overhanging either end.  When the shorter
  string is empty, 100.0 is returned (an empty window matches perfectly) —
  mirroring rapidfuzz's behaviour for empty needles.

This pure-Python version is the oracle for tests and small inputs.  A C++
twin (bit-parallel Hyyrö LCS, planned as ``native/fastmatch.cpp``) will be
the production verifier behind the TPU q-gram screen once the matcher
pipeline lands; until then this module is the only implementation.
"""

from __future__ import annotations

from functools import lru_cache


def _lcs_len(a: str, b: str) -> int:
    """Classic O(|a|·|b|) LCS-length DP (row-rolling)."""
    if not a or not b:
        return 0
    if len(a) < len(b):
        a, b = b, a
    prev = [0] * (len(b) + 1)
    for ca in a:
        cur = [0] * (len(b) + 1)
        for j, cb in enumerate(b, 1):
            cur[j] = prev[j - 1] + 1 if ca == cb else max(prev[j], cur[j - 1])
        prev = cur
    return prev[-1]


def indel_distance(s1: str, s2: str) -> int:
    return len(s1) + len(s2) - 2 * _lcs_len(s1, s2)


def ratio(s1: str, s2: str) -> float:
    total = len(s1) + len(s2)
    if total == 0:
        return 100.0
    return 100.0 * (1.0 - indel_distance(s1, s2) / total)


def partial_ratio(s1: str, s2: str) -> float:
    shorter, longer = (s1, s2) if len(s1) <= len(s2) else (s2, s1)
    m, n = len(shorter), len(longer)
    if m == 0:
        return 100.0
    best = 0.0
    # Every window of length m, plus the overhanging partial windows.
    for start in range(-(m - 1), n):
        lo, hi = max(0, start), min(n, start + m)
        if hi <= lo:
            continue
        sc = ratio(shorter, longer[lo:hi])
        if sc > best:
            best = sc
            if best >= 100.0:
                break
    return best


@lru_cache(maxsize=65536)
def partial_ratio_cached(s1: str, s2: str) -> float:
    return partial_ratio(s1, s2)
