"""ctypes loader for the C++ CSV column scanner (``native/csvscan.cpp``).

Same compile-on-demand contract as ``cpu/native.py``: built with g++ on
first use (mtime-cached .so), silent fallback to the Python ``csv`` module
when no compiler is available — ``storage/csvio.py`` stays correct either
way, the native path is just fast on the multi-GB resume files.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native", "csvscan.cpp")
_LIB = os.path.join(os.path.dirname(_SRC), "libcsvscan.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
BACKEND = "unloaded"


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _LIB],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, BACKEND
    with _lock:
        if BACKEND != "unloaded":
            return _lib
        needs_build = (not os.path.exists(_LIB)) or (
            os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        )
        if needs_build and not _build():
            BACKEND = "python"
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            BACKEND = "python"
            return None
        lib.csv_scan_column.restype = ctypes.POINTER(ctypes.c_char)
        lib.csv_scan_column.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_longlong),
        ]
        lib.csv_free.restype = None
        lib.csv_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
        BACKEND = "native"
        _lib = lib
        return lib


def scan_column(path: str, column: str) -> list[str] | None:
    """All values of ``column`` from a well-formed CSV, or ``None`` when
    the native library is unavailable, the file/column is missing, or the
    bytes are not valid UTF-8 (callers fall back to the csv module)."""
    lib = _load()
    if lib is None:
        return None
    count = ctypes.c_longlong()
    nbytes = ctypes.c_longlong()
    ptr = lib.csv_scan_column(
        path.encode("utf-8"), column.encode("utf-8"),
        ctypes.byref(count), ctypes.byref(nbytes),
    )
    if not ptr:
        return None
    try:
        raw = ctypes.string_at(ptr, nbytes.value)
    finally:
        lib.csv_free(ptr)
    if count.value == 0:
        return []
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError:
        return None  # Python open() would also have raised; let csv path decide
    vals = text.split("\0")
    assert vals and vals[-1] == ""  # arena is value+NUL repeated
    vals.pop()
    if len(vals) != count.value:
        return None  # a value contained NUL — ambiguous split; fall back
    return vals
