"""Shared build-or-find logic for the on-demand native kernels.

Every native helper (``hostbatch``, ``exactdedup``, …) compiles its .so
beside its source on first use.  Two silent failure modes used to route
hot paths onto Python fallbacks with no trace — BENCH_r05's exact
regime ran the 12×-slower grouping fallback for a whole round before
anyone noticed (ISSUE 9):

- the repo directory may be unwritable under a harness (read-only
  checkout, sandbox) — ``g++ -o <repo>/lib*.so`` fails even though the
  compiler works; and
- the failure reason (no g++, missing Python.h, timeout, unwritable
  target) was swallowed by a bare ``except``.

:func:`build_or_find` fixes both: it tries the canonical beside-source
path first, then a per-user temp-dir fallback, and remembers WHY the
last attempt failed so loaders can expose it
(``exactdedup.backend_reason()`` → bench JSON ``exact_backend_reason``).
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile


def fallback_lib_path(lib_path: str) -> str:
    """Per-user temp-dir twin of a beside-source .so path.

    The filename carries a short hash of the canonical path: two
    checkouts of the repo on one machine (CI sandboxes, worktrees) must
    never share a fallback .so — a fresh-looking binary built from the
    OTHER checkout's source would load silently."""
    tag = f"astpu-native-{os.getuid() if hasattr(os, 'getuid') else 'u'}"
    digest = hashlib.sha1(
        os.path.abspath(lib_path).encode("utf-8")
    ).hexdigest()[:10]
    base, ext = os.path.splitext(os.path.basename(lib_path))
    return os.path.join(
        tempfile.gettempdir(), tag, f"{base}-{digest}{ext}"
    )


def _fallback_dir_trusted(lib: str, create: bool) -> bool:
    """The fallback dir is trusted only when THIS user owns it and no one
    else can write it — ``ctypes`` will dlopen whatever sits there, and
    the tag name under the world-writable temp dir is predictable, so an
    attacker-planted directory (or .so) must never be honoured."""
    d = os.path.dirname(lib)
    if create:
        try:
            os.makedirs(d, mode=0o700, exist_ok=True)
        except Exception:
            return False
    try:
        st = os.stat(d)
    except OSError:
        return False
    if hasattr(os, "getuid") and st.st_uid != os.getuid():
        return False
    return not st.st_mode & 0o022  # no group/other write


def _fresh(lib: str, src: str) -> bool:
    return os.path.exists(lib) and os.path.getmtime(lib) >= os.path.getmtime(
        src
    )


def find_fresh(src: str, lib_path: str) -> str | None:
    """An already-built, up-to-date .so for ``src`` (canonical path or
    owner-verified temp-dir fallback), or None.  Lets loaders with
    build-only prerequisites (e.g. CPython headers) serve a prebuilt
    library on hosts that could not have compiled it."""
    if _fresh(lib_path, src):
        return lib_path
    fb = fallback_lib_path(lib_path)
    if _fallback_dir_trusted(fb, create=False) and _fresh(fb, src):
        return fb
    return None


def build_or_find(
    src: str, lib_path: str, extra_flags: tuple[str, ...] = ()
) -> tuple[str | None, str]:
    """``(path_to_fresh_so | None, reason)``.

    Candidates in order: the canonical ``lib_path`` (beside the source),
    then :func:`fallback_lib_path` under the temp dir.  A candidate that
    is already fresh (mtime ≥ source) wins without compiling; otherwise
    a ``g++`` build into it is attempted.  On total failure the second
    element says why (compiler stderr tail, missing toolchain, …) so the
    caller can surface it instead of silently degrading.
    """
    # fresh candidates first — BOTH of them, before any build attempt:
    # a missing compiler must not hide a loadable prebuilt fallback
    found = find_fresh(src, lib_path)
    if found is not None:
        return found, ""
    reasons: list[str] = []
    fb = fallback_lib_path(lib_path)
    for target in (lib_path, fb):
        if target is fb and not _fallback_dir_trusted(fb, create=True):
            reasons.append(f"fallback dir for {fb} not owned/private")
            continue
        try:
            os.makedirs(os.path.dirname(target), exist_ok=True)
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", *extra_flags, src,
                 "-o", target],
                check=True,
                capture_output=True,
                timeout=120,
            )
            return target, ""
        except FileNotFoundError:
            reasons.append("g++ not found")
            break  # no compiler: the fallback dir won't help
        except subprocess.CalledProcessError as e:
            tail = (e.stderr or b"").decode("utf-8", "replace")[-200:]
            reasons.append(f"g++ failed for {target}: {tail.strip()}")
        except Exception as e:  # timeout, unwritable dir, ...
            reasons.append(f"build into {target}: {type(e).__name__}: {e}")
    return None, "; ".join(reasons) or "unknown build failure"
