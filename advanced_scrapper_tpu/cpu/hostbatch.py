"""Host feed queue + batcher bindings (``native/hostbatch.cpp``).

:class:`HostBatcher` is the CPU→TPU boundary of the streaming pipelines: the
fetch/extract side pushes variable-length byte documents (with a uint64 tag
the caller uses to map rows back to records), the device side pops
zero-padded ``uint8[batch, block]`` tiles ready for ``jax.device_put``.
Assembly is native C++ (memcpy/memset under one mutex) per SURVEY.md §7.3's
"host queue + batcher implemented in C++"; a pure-Python twin with the same
API keeps the framework importable without a compiler, and
:data:`hostbatch_backend` reports which is live.

Backpressure: ``push`` returns False when the doc or arena cap is hit —
producers block/drop by policy, the queue never grows unbounded (the
reference's unbounded ``queue.Queue`` at ``constant_rate_scrapper.py:146``
could).
"""

from __future__ import annotations

import collections
import ctypes
import os
import threading
import time
from typing import Iterable

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native", "hostbatch.cpp")
_LIB = os.path.join(os.path.dirname(_SRC), "libhostbatch.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_backend = "unloaded"
_reason = ""  # why the native backend is unavailable ("" when it is)


def _load() -> ctypes.CDLL | None:
    global _lib, _backend, _reason
    with _lock:
        if _backend != "unloaded":
            return _lib
        from advanced_scrapper_tpu.cpu.nativebuild import build_or_find

        # build beside the source, falling back to a per-user temp dir
        # when the repo is unwritable; keep the failure reason for
        # reporting (a silently-degraded batcher/encoder costs the whole
        # stream/ragged path, not just one call site)
        lib_path, why = build_or_find(_SRC, _LIB)
        if lib_path is None:
            _backend, _reason = "python", why
            return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError as e:
            _backend, _reason = "python", f"load failed: {e}"
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.hb_create.restype = ctypes.c_void_p
        lib.hb_create.argtypes = [ctypes.c_long, ctypes.c_long]
        lib.hb_push.restype = ctypes.c_int
        # c_char_p: bytes pass zero-copy (the C side copies into its arena;
        # explicit length keeps embedded NULs intact)
        lib.hb_push.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long, ctypes.c_uint64
        ]
        lib.hb_push_many.restype = ctypes.c_long
        lib.hb_push_many.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_long,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.hb_pop_batch.restype = ctypes.c_long
        lib.hb_pop_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.c_long, ctypes.c_long,
            u8p, ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.hb_encode_blocks.restype = ctypes.c_long
        lib.hb_encode_blocks.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong),
            ctypes.c_long, ctypes.c_long, ctypes.c_long, ctypes.c_long,
            u8p, ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ]
        # Newer kernels (this PR's host-path overhaul): guard each so a
        # stale .so that predates them degrades to the old behaviour
        # instead of failing the whole native backend.
        if hasattr(lib, "hb_pop_batch_min"):
            lib.hb_pop_batch_min.restype = ctypes.c_long
            lib.hb_pop_batch_min.argtypes = [
                ctypes.c_void_p, ctypes.c_long, ctypes.c_long, ctypes.c_long,
                ctypes.c_long, u8p, ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_uint64),
            ]
        if hasattr(lib, "hb_encode_ranges"):
            lib.hb_encode_ranges.restype = ctypes.c_long
            lib.hb_encode_ranges.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong),
                ctypes.POINTER(ctypes.c_longlong), ctypes.c_long,
                ctypes.c_long, ctypes.c_long, ctypes.c_long,
                u8p, ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
            ]
        if hasattr(lib, "hb_exact_keep_first"):
            lib.hb_exact_keep_first.restype = ctypes.c_long
            lib.hb_exact_keep_first.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong),
                ctypes.c_long, u8p,
            ]
        for name in ("hb_size", "hb_arena_used"):
            getattr(lib, name).restype = ctypes.c_long
            getattr(lib, name).argtypes = [ctypes.c_void_p]
        lib.hb_closed.restype = ctypes.c_int
        lib.hb_closed.argtypes = [ctypes.c_void_p]
        for name in ("hb_stat_pushed", "hb_stat_popped", "hb_stat_rejected"):
            getattr(lib, name).restype = ctypes.c_uint64
            getattr(lib, name).argtypes = [ctypes.c_void_p]
        lib.hb_close.restype = None
        lib.hb_close.argtypes = [ctypes.c_void_p]
        lib.hb_destroy.restype = None
        lib.hb_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        _backend = "native"
        return lib


def hostbatch_backend() -> str:
    """'native' or 'python' (after first use)."""
    _load()
    return _backend


def backend_reason() -> str:
    """Why the native backend is unavailable — "" when it is live."""
    _load()
    return _reason


def _enc(doc: str | bytes) -> bytes:
    return doc if isinstance(doc, bytes) else doc.encode("utf-8", "replace")


def encode_blocks_native(
    raw: list[bytes], block_len: int, overlap: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
    """Native blockwise split+pad (``hb_encode_blocks``); None when no
    compiler is available (callers fall back to the Python loop in
    ``core.tokenizer.encode_blocks``, the behavioural oracle).

    The block count per doc is computed vectorised here, output arrays are
    preallocated zero-filled, and one C call does every memcpy — the Python
    cost is O(docs) (the ``b"".join``), not O(blocks), which is what lets a
    100 kB tail article cost one join instead of ~100 interpreter loop turns
    (the round-2 ragged-regime bottleneck).
    """
    lib = _load()
    if lib is None:
        return None
    if block_len <= overlap:
        raise ValueError(f"block_len {block_len} must exceed overlap {overlap}")
    n = len(raw)
    stride = block_len - overlap
    lens = np.fromiter((len(r) for r in raw), dtype=np.int64, count=n)
    offsets = np.zeros((n + 1,), dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    # blocks per doc: smallest m with (m-1)*stride + block_len >= len
    counts = np.where(
        lens > block_len, (lens - block_len + stride - 1) // stride + 1, 1
    )
    total = int(counts.sum())
    tokens = np.zeros((total, block_len), dtype=np.uint8)
    out_lens = np.zeros((total,), dtype=np.int32)
    owners = np.zeros((total,), dtype=np.int32)
    blob = b"".join(raw)
    wrote = lib.hb_encode_blocks(
        blob,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        n,
        block_len,
        overlap,
        total,
        tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        owners.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if wrote != total:
        raise RuntimeError(
            f"hb_encode_blocks wrote {wrote} blocks, expected {total}"
        )
    return tokens, out_lens, owners


def encode_blocks_ranges(
    blob: bytes,
    starts: np.ndarray,
    lens: np.ndarray,
    counts: np.ndarray,
    block_len: int,
    overlap: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
    """Encode arbitrary (start, len) byte ranges of ``blob`` blockwise
    (see ``hb_encode_ranges`` for why ranges: tail blocks of long documents
    route to narrower width buckets).  ``counts`` = per-range block counts (``block_counts`` over
    the range lens).  Returns ``(tokens, lengths, owners)`` with owners
    indexing into the range arrays, or None without a native library.
    """
    lib = _load()
    if lib is None or not hasattr(lib, "hb_encode_ranges"):
        return None
    if block_len <= overlap:
        raise ValueError(f"block_len {block_len} must exceed overlap {overlap}")
    total = int(counts.sum())
    tokens = np.zeros((total, block_len), dtype=np.uint8)
    out_lens = np.zeros((total,), dtype=np.int32)
    owners = np.zeros((total,), dtype=np.int32)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    wrote = lib.hb_encode_ranges(
        blob,
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        len(starts),
        block_len,
        overlap,
        total,
        tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        owners.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if wrote != total:
        raise RuntimeError(
            f"hb_encode_ranges wrote {wrote} blocks, expected {total}"
        )
    return tokens, out_lens, owners


def block_counts(lens: np.ndarray, block_len: int, overlap: int) -> np.ndarray:
    """Vectorised blocks-per-doc for the blockwise split (smallest m with
    ``(m-1)*stride + block_len >= len``; empty docs still take one block)."""
    stride = block_len - overlap
    return np.where(
        lens > block_len, (lens - block_len + stride - 1) // stride + 1, 1
    )


def exact_keep_first_native(items) -> np.ndarray | None:
    """``uint8[n]`` first-seen keep mask over ``items`` via the single-pass
    native hash table (``hb_exact_keep_first``), or None when the native
    library (or the symbol, on a stale .so) is unavailable / the items
    cannot be flattened losslessly.

    Strings are flattened with ONE ``"".join`` + one UTF-8 encode
    (surrogatepass: injective on every str, so byte equality ⟺ string
    equality — a lossy errors-mode could collapse two distinct items into
    the same bytes and wrongly drop one).  Byte lengths come from the char
    lengths when the blob is pure ASCII; otherwise each item re-encodes
    once (the rare non-ASCII corpus).  Mixed str/bytes inputs return None
    (the caller's confirm-on-collision fallback handles them).
    """
    lib = _load()
    if lib is None or not hasattr(lib, "hb_exact_keep_first"):
        return None
    n = len(items)
    if n == 0:
        return np.zeros((0,), np.uint8)
    try:
        blob_s = "".join(items)
    except TypeError:
        try:
            blob = b"".join(items)
        except TypeError:
            return None  # mixed str/bytes: no lossless single flattening
        lens = np.fromiter(map(len, items), np.int64, count=n)
    else:
        if blob_s.isascii():  # one scan; char lens == byte lens
            blob = blob_s.encode("utf-8")
            lens = np.fromiter(map(len, items), np.int64, count=n)
        else:  # per-item encode is needed for byte lens anyway — do it once
            raw = [s.encode("utf-8", "surrogatepass") for s in items]
            blob = b"".join(raw)
            lens = np.fromiter(map(len, raw), np.int64, count=n)
    offsets = np.zeros((n + 1,), dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    keep = np.zeros((n,), dtype=np.uint8)
    rc = lib.hb_exact_keep_first(
        blob,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        n,
        keep.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if rc < 0:
        return None  # allocation failure: fall back rather than crash
    return keep


class _NativeBatcher:
    def __init__(self, lib: ctypes.CDLL, max_docs: int, arena_bytes: int):
        self._lib = lib
        self._h = ctypes.c_void_p(lib.hb_create(max_docs, arena_bytes))
        # serializes destroy() against the introspection surface (size /
        # arena_used / stats / closed), which telemetry callback gauges
        # read from exporter threads: a bare `if not self._h` check would
        # be check-then-use — destroy() could free the handle between the
        # check and the C call, and the C side locks a member mutex with
        # no NULL check.  Push/pop are NOT covered: they belong to the
        # producer/consumer threads whose lifecycle already ends before
        # destroy (the pre-existing contract).
        self._destroy_mu = threading.Lock()

    def push(self, doc: bytes, tag: int) -> bool:
        return bool(self._lib.hb_push(self._h, doc, len(doc), tag))

    def push_many(self, docs: list[bytes], tags) -> int:
        """One C call for a whole list; returns docs accepted (prefix)."""
        n = min(len(docs), len(tags))  # zip-truncate like the Python twin;
        docs = docs[:n]                # C reads exactly n tags — no OOB
        if n == 0:
            return 0
        offsets = np.zeros((n + 1,), dtype=np.int64)
        np.cumsum([len(d) for d in docs], out=offsets[1:])
        blob = b"".join(docs)
        tag_arr = np.ascontiguousarray(tags, dtype=np.uint64)
        return int(
            self._lib.hb_push_many(
                self._h,
                blob,
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
                n,
                tag_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            )
        )

    def pop_batch(self, batch: int, block: int, timeout_ms: int, min_fill: int = 1):
        tokens = np.zeros((batch, block), dtype=np.uint8)
        lengths = np.zeros((batch,), dtype=np.int32)
        tags = np.zeros((batch,), dtype=np.uint64)
        outs = (
            tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            tags.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        )
        if min_fill > 1 and hasattr(self._lib, "hb_pop_batch_min"):
            n = self._lib.hb_pop_batch_min(
                self._h, batch, block, timeout_ms, min_fill, *outs
            )
        else:
            n = self._lib.hb_pop_batch(self._h, batch, block, timeout_ms, *outs)
        return int(n), tokens, lengths, tags

    def size(self) -> int:
        # scrape-time surface: a destroyed handle reads as empty (guarded
        # by _destroy_mu so the handle cannot be freed mid-call)
        with self._destroy_mu:
            if not self._h:
                return 0
            return int(self._lib.hb_size(self._h))

    def arena_used(self) -> int:
        with self._destroy_mu:
            if not self._h:
                return 0
            return int(self._lib.hb_arena_used(self._h))

    def stats(self) -> dict:
        with self._destroy_mu:
            if not self._h:
                return {"pushed": 0, "popped": 0, "rejected": 0}
            return {
                "pushed": int(self._lib.hb_stat_pushed(self._h)),
                "popped": int(self._lib.hb_stat_popped(self._h)),
                "rejected": int(self._lib.hb_stat_rejected(self._h)),
            }

    def closed(self) -> bool:
        with self._destroy_mu:
            if not self._h:
                return True
            return bool(self._lib.hb_closed(self._h))

    def close(self) -> None:
        with self._destroy_mu:
            if self._h:
                self._lib.hb_close(self._h)

    def destroy(self) -> None:
        with self._destroy_mu:
            if self._h:
                self._lib.hb_destroy(self._h)
                self._h = None


class _PyBatcher:
    """Pure-Python twin of the native queue (same semantics, for fallback
    and as the behavioural oracle in tests)."""

    def __init__(self, max_docs: int, arena_bytes: int):
        self._max_docs = max_docs if max_docs > 0 else float("inf")
        self._arena_cap = arena_bytes if arena_bytes > 0 else float("inf")
        self._q: collections.deque[tuple[bytes, int]] = collections.deque()
        self._arena = 0
        self._cv = threading.Condition()
        self._closed = False
        self._pushed = self._popped = self._rejected = 0

    def push(self, doc: bytes, tag: int) -> bool:
        with self._cv:
            if (
                self._closed
                or len(self._q) >= self._max_docs
                or self._arena + len(doc) > self._arena_cap
            ):
                self._rejected += 1
                # wake min_fill waiters: a queue that rejects pushes can't
                # grow to their fill target — they must drain instead
                self._cv.notify_all()
                return False
            self._q.append((doc, tag))
            self._arena += len(doc)
            self._pushed += 1
            self._cv.notify()
            return True

    def push_many(self, docs: list[bytes], tags) -> int:
        n = 0
        for doc, tag in zip(docs, tags):
            if not self.push(doc, int(tag)):
                break
            n += 1
        return n

    def pop_batch(self, batch: int, block: int, timeout_ms: int, min_fill: int = 1):
        tokens = np.zeros((batch, block), dtype=np.uint8)
        lengths = np.zeros((batch,), dtype=np.int32)
        tags = np.zeros((batch,), dtype=np.uint64)
        # clamp to capacity too: a fill the queue can never hold must not
        # turn a timeout_ms=-1 pop into a deadlock-until-close; likewise any
        # push REJECTED while waiting (doc/arena backpressure) proves the
        # fill target is unreachable right now — drain instead of starving
        want = max(1, min(min_fill, batch, self._max_docs))
        with self._cv:
            rej0 = self._rejected
            if len(self._q) < want and not self._closed and timeout_ms != 0:
                deadline = None if timeout_ms < 0 else time.monotonic() + timeout_ms / 1e3
                while (
                    len(self._q) < want
                    and not self._closed
                    and self._rejected == rej0
                ):
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        break  # timeout: drain whatever is there (may be 0)
                    self._cv.wait(remaining)
            n = 0
            while n < batch and self._q:
                doc, tag = self._q.popleft()
                self._arena -= len(doc)
                self._popped += 1
                copy = min(len(doc), block)
                if copy:
                    tokens[n, :copy] = np.frombuffer(doc[:copy], dtype=np.uint8)
                lengths[n] = copy
                tags[n] = tag
                n += 1
        return n, tokens, lengths, tags

    def size(self) -> int:
        with self._cv:
            return len(self._q)

    def arena_used(self) -> int:
        with self._cv:
            return self._arena

    def stats(self) -> dict:
        with self._cv:
            return {
                "pushed": self._pushed,
                "popped": self._popped,
                "rejected": self._rejected,
            }

    def closed(self) -> bool:
        with self._cv:
            return self._closed

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def destroy(self) -> None:
        pass


class HostBatcher:
    """The CPU→TPU feed queue (native C++ when a compiler is available).

    Args:
      block: byte length of each token row (documents truncate here).
      max_docs: queue capacity in documents (<=0 → unbounded).
      arena_bytes: total buffered-byte cap (<=0 → unbounded).
      prefer_native: force the pure-Python twin with False.
    """

    def __init__(
        self,
        block: int,
        *,
        max_docs: int = 65536,
        arena_bytes: int = 1 << 30,
        prefer_native: bool = True,
    ):
        self.block = block
        lib = _load() if prefer_native else None
        if lib is not None:
            self._impl = _NativeBatcher(lib, max_docs, arena_bytes)
            self.backend = "native"
        else:
            self._impl = _PyBatcher(max_docs, arena_bytes)
            self.backend = "python"

    def push(self, doc: str | bytes, tag: int) -> bool:
        """Queue one document; False = backpressure (caller retries/drops)."""
        return self._impl.push(_enc(doc), tag)

    def push_many(self, docs, tags) -> int:
        """Queue a list in one native call (~3× the one-at-a-time rate);
        returns the accepted prefix length — backpressure stops the rest.
        ``tags`` may be any iterable; generators are materialised (and
        truncated to the doc count) so both backends behave identically;
        sized inputs (lists, ndarrays) slice without a per-element
        round-trip."""
        docs = [_enc(d) for d in docs]
        try:
            tags = tags[: len(docs)]
        except TypeError:  # sized-but-unsliceable (set, dict keys) or generator
            import itertools

            tags = list(itertools.islice(iter(tags), len(docs)))
        return self._impl.push_many(docs, tags)

    def push_blocking(
        self, doc: str | bytes, tag: int, *, poll_s: float = 0.005, timeout_s: float = 60.0
    ) -> bool:
        """Push with bounded blocking backpressure."""
        data = _enc(doc)
        deadline = time.monotonic() + timeout_s
        while not self._impl.push(data, tag):
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)
        return True

    def pop_batch(
        self, batch: int, *, timeout_ms: int = -1, min_fill: int = 1
    ) -> tuple[int, np.ndarray, np.ndarray, np.ndarray]:
        """Pop ≤``batch`` docs as ``(n, tokens[batch, block], lengths, tags)``.

        Blocks up to ``timeout_ms`` until at least ``min_fill`` documents are
        queued (−1 = forever, 0 = no wait) then drains greedily; rows past
        ``n`` are zero padding.  ``min_fill=1`` (the default) is the classic
        pop-on-first-doc behaviour; ``min_fill=batch`` assembles FULL tiles —
        the staging discipline of :class:`pipeline.feed.DeviceFeed`, where a
        partial tile still pays a full-shape device kernel.  A timeout or a
        closed queue always hands over whatever is buffered, so a slow
        producer degrades to partial tiles instead of starving the device.
        ``n == 0`` means timeout-while-empty or closed-and-empty.
        """
        return self._impl.pop_batch(batch, self.block, timeout_ms, min_fill)

    def feed(
        self,
        docs: Iterable[str | bytes],
        *,
        start_tag: int = 0,
        timeout_s: float = 60.0,
        chunk: int = 1024,
    ) -> int:
        """Push an iterable with sequential tags; returns count.

        Chunks through :meth:`push_many` — the batched native call is what
        actually out-runs the device (1.03M vs 0.49M docs/s one-at-a-time;
        DESIGN.md §5).  Each chunk's rejected suffix retries under bounded
        backpressure; on timeout the remaining docs are dropped and the
        count returned reflects what was queued.
        """
        import itertools

        n = 0
        tag = start_tag
        it = iter(docs)
        while True:
            batch = [_enc(d) for d in itertools.islice(it, chunk)]
            if not batch:
                return n
            deadline = time.monotonic() + timeout_s
            while batch:
                acc = self._impl.push_many(
                    batch, list(range(tag, tag + len(batch)))
                )
                n += acc
                tag += acc
                batch = batch[acc:]
                if acc:
                    # progress resets the clock — only a consumer making NO
                    # progress for timeout_s drops docs (parity with the old
                    # per-document push_blocking semantics)
                    deadline = time.monotonic() + timeout_s
                if batch:
                    if self.closed():
                        return n  # nobody will accept the rest — stop now
                    if time.monotonic() >= deadline:
                        return n
                    time.sleep(0.005)

    def size(self) -> int:
        return self._impl.size()

    def arena_used(self) -> int:
        return self._impl.arena_used()

    def stats(self) -> dict:
        return self._impl.stats()

    def closed(self) -> bool:
        return self._impl.closed()

    def close(self) -> None:
        """Stop accepting pushes; wake blocked pops (they drain then return 0)."""
        self._impl.close()

    def __enter__(self) -> "HostBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
        self._impl.destroy()
