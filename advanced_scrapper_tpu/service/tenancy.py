"""Tenant declarations: namespaces, quota specs and the registry.

A tenant is a name plus a quota: the name maps — via
:func:`tenant_space` — into the reserved ``tenant:`` key-space prefix
the index plane's :func:`~advanced_scrapper_tpu.index.remote
.namespace_policy` table declares (auto-provisioned on first touch,
wipe-allowed for offboarding), so a tenant's band keys cannot collide
with another tenant's or with the shared ``bands``/``urls`` spaces BY
CONSTRUCTION — isolation is a property of the key space, not of any
routing code being correct.

The quota half is declarative too: :class:`TenantSpec` carries the
token-bucket rate/burst, the concurrency cap and the tenant's SLO
targets (p99 ceiling + allowed reject ratio), and
:class:`TenantRegistry` resolves ids to specs — either pre-declared
(``auto_provision=False``: an unknown tenant is refused, the closed
deployment) or stamped from a default template on first sight (the open
deployment the canary prober's auto-provisioned spaces pioneered).
"""

from __future__ import annotations

import dataclasses
import re
import threading

from advanced_scrapper_tpu.index.remote import TENANT_SPACE_PREFIX

__all__ = [
    "TENANT_ID_RE",
    "TenantRegistry",
    "TenantSpec",
    "tenant_space",
]

#: tenant ids travel inside key-space names (``tenant:<id>:<sub>``) and
#: metric label values, so the charset is deliberately narrow — in
#: particular no ``:``, which would let one tenant's id parse as
#: another's id + sub-space.
TENANT_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]{0,63}$")


def tenant_space(tenant: str, sub: str = "bands") -> str:
    """The key-space name for one tenant's sub-index (default: the band
    postings).  Raises ``ValueError`` for ids outside the narrow charset
    — a malformed id must fail before it names a key space."""
    if not TENANT_ID_RE.match(tenant or ""):
        raise ValueError(f"invalid tenant id {tenant!r}")
    return f"{TENANT_SPACE_PREFIX}{tenant}:{sub}"


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's declared quota + objectives.

    - ``rate``/``burst`` — the tenant's own token bucket (requests/s;
      0 = uncapped), stacked UNDER the gateway's shared admission gate;
    - ``max_inflight`` — the tenant's concurrency cap (0 = uncapped);
    - ``p99_slo_s`` — the per-tenant p99 latency ceiling the SLO engine
      evaluates over ``astpu_tenant_seconds{tenant=…}``;
    - ``reject_budget`` — the allowed rejected/requests ratio before the
      tenant's quota objective burns;
    - ``slo_budget`` — the violating window fraction both objectives
      tolerate (the engine's error budget).
    """

    tenant: str
    rate: float = 0.0
    burst: float | None = None
    max_inflight: int = 16
    p99_slo_s: float = 0.5
    reject_budget: float = 0.5
    slo_budget: float = 0.05

    def __post_init__(self):
        if not TENANT_ID_RE.match(self.tenant or ""):
            raise ValueError(f"invalid tenant id {self.tenant!r}")

    @classmethod
    def parse(cls, text: str) -> "TenantSpec":
        """``"name[,rate=R][,burst=B][,inflight=N][,p99=S][,rejects=F]"``
        — the CLI shape (``--tenant acme,rate=500,inflight=8``)."""
        parts = [p.strip() for p in text.split(",") if p.strip()]
        if not parts:
            raise ValueError("empty tenant spec")
        kw: dict = {"tenant": parts[0]}
        keys = {
            "rate": ("rate", float),
            "burst": ("burst", float),
            "inflight": ("max_inflight", int),
            "p99": ("p99_slo_s", float),
            "rejects": ("reject_budget", float),
            "budget": ("slo_budget", float),
        }
        for part in parts[1:]:
            k, sep, v = part.partition("=")
            if not sep or k not in keys:
                raise ValueError(f"bad tenant spec field {part!r}")
            field, conv = keys[k]
            kw[field] = conv(v)
        return cls(**kw)


class TenantRegistry:
    """Thread-safe id → :class:`TenantSpec` resolution.

    Pre-declared specs always win; unknown ids either stamp a fresh spec
    from the ``default`` template (``auto_provision=True`` — mirroring
    the namespace table's auto-provisioned ``tenant:`` prefix) or raise
    ``KeyError`` (closed deployment: the front door refuses tenants
    nobody declared)."""

    def __init__(
        self,
        specs=(),
        *,
        default: TenantSpec | None = None,
        auto_provision: bool = True,
    ):
        self._lock = threading.Lock()
        self._specs: dict[str, TenantSpec] = {}
        self.default = default or TenantSpec(tenant="default")
        self.auto_provision = bool(auto_provision)
        self._declared: set[str] = set()
        for spec in specs:
            self.register(spec)

    def register(self, spec: TenantSpec) -> TenantSpec:
        with self._lock:
            self._specs[spec.tenant] = spec
            self._declared.add(spec.tenant)
        return spec

    def get(self, tenant: str) -> TenantSpec:
        if not TENANT_ID_RE.match(tenant or ""):
            raise KeyError(f"invalid tenant id {tenant!r}")
        with self._lock:
            spec = self._specs.get(tenant)
            if spec is not None:
                return spec
            if not self.auto_provision:
                raise KeyError(f"unknown tenant {tenant!r}")
            spec = dataclasses.replace(self.default, tenant=tenant)
            self._specs[tenant] = spec
            return spec

    def known(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._specs))

    def declared(self) -> tuple[str, ...]:
        """Operator-declared ids only — auto-provisioned walk-ins are
        ``known()`` but not declared (the status surface tells the two
        apart, so an operator can spot tenants nobody budgeted for)."""
        with self._lock:
            return tuple(sorted(self._declared))
