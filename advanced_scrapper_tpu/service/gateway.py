"""The dedup-as-a-service front door: one RPC gateway, many tenants.

:class:`DedupGateway` serves four verbs over the length-framed RPC plane
(``net/rpc.py``) — ``submit_batch`` (check-and-add a batch of band-key
rows under the caller's doc ids, or allocate ids server-side),
``probe_batch`` (read-only attribution), ``query`` (single-doc point
lookup) and ``tenant_status`` (the ungated control surface) — plus the
offboarding verb ``wipe_tenant``.  Every gated request carries a
``tenant`` header field; the gateway resolves it through the
:class:`~advanced_scrapper_tpu.service.tenancy.TenantRegistry` and
routes it to a per-tenant sibling fleet client
(``ShardedIndexClient.for_space``) over the ``tenant:<id>:bands`` key
space, so cross-tenant collisions are impossible by construction — the
namespace policy table in ``index/remote.py`` auto-provisions the space
shard-side and keeps ``wipe`` prefix-guarded.

**Quota stacking.**  Each tenant gets its own
:class:`~advanced_scrapper_tpu.runtime.admission.AdmissionController`
(token bucket + concurrency cap, named ``tenant:<id>``), wired into the
transport through ``RpcServer``'s per-request ``admission_resolver`` —
NOT raised from handlers, because a handler exception is remembered
under the request id and would replay a stale refusal; the resolver path
answers an uncached, counted ``RpcOverloaded`` carrying the bucket's
retry-after, which ``RpcClient`` honors before retrying under the same
id.  The tenant gate stacks UNDER the gateway's shared controller:
a tenant over quota is stopped at its own bucket (billed to its own
``astpu_admission_pressure{gate="tenant:<id>"}`` series) without
consuming a shared slot.  Critical-priority traffic and the control
surface are never refused.

**Observability.**  The gateway owns the ``astpu_tenant_*`` series
(always-on, like every admission counter): per-tenant/verb request and
latency series, per-tenant reject counts, and a posting-count gauge fed
from budget-guarded fleet stats.  :meth:`DedupGateway.objectives` emits
the per-tenant p99 + reject-ratio objectives the PR 11 SLO engine
evaluates, and the per-tenant admission pressure feeds
``runtime.autoscaler.admission_pressure()`` automatically — a noisy
tenant raises the fleet-wide pressure max and triggers scale-out (or
walks its own bucket's shed) instead of starving neighbors.

``python -m advanced_scrapper_tpu.service.gateway --shard h:p,h:p …``
serves a gateway standalone (jax-free, fork-cheap, SIGTERM-clean — the
same process contract as the shard server).
"""

from __future__ import annotations

import threading
import time

import numpy as np

import advanced_scrapper_tpu.net.rpc as rpc

from advanced_scrapper_tpu.index.fleet import FleetSpec, ShardedIndexClient
from advanced_scrapper_tpu.runtime.admission import (
    AdmissionController,
    PRIORITY_CRITICAL,
    PRIORITY_LOW,
    PRIORITY_NORMAL,
)
from advanced_scrapper_tpu.service.tenancy import (
    TenantRegistry,
    TenantSpec,
    tenant_space,
)

__all__ = ["DedupGateway", "GATED_VERBS", "serve_main"]

#: verbs that pay admission (the shared gate AND the tenant bucket);
#: ``tenant_status`` / ``wipe_tenant`` / ``__ping__`` stay ungated — an
#: overloaded front door must remain observable and offboardable.
GATED_VERBS = frozenset({"submit_batch", "probe_batch", "query"})


class _Tenant:
    """One provisioned tenant's live state: spec, bucket, fleet client."""

    __slots__ = ("spec", "ctrl", "client")

    def __init__(self, spec: TenantSpec, ctrl, client):
        self.spec = spec
        self.ctrl = ctrl
        self.client = client


class _BoundGate:
    """The per-request admission gate handed to ``RpcServer``: delegates
    to the tenant's controller and bills the refusal to the gateway's
    per-tenant reject/request series (the controller's own
    ``astpu_admission_*`` series fire too — this is the tenant-labeled
    view the SLO objectives match on)."""

    __slots__ = ("gw", "tenant", "verb")

    def __init__(self, gw: "DedupGateway", tenant: _Tenant, verb: str):
        self.gw = gw
        self.tenant = tenant
        self.verb = verb

    def admit(self, priority):
        d = self.tenant.ctrl.admit(priority)
        if not d.admitted:
            tid = self.tenant.spec.tenant
            self.gw._req_counter(tid, self.verb, "rejected").inc()
            self.gw._reject_counter(tid, d.reason or "quota").inc()
        return d

    def release(self, decision) -> None:
        self.tenant.ctrl.release(decision)


class DedupGateway:
    """The multi-tenant front door over one index fleet client."""

    def __init__(
        self,
        client: ShardedIndexClient,
        *,
        registry: TenantRegistry | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        name: str = "gateway",
        admission: AdmissionController | None = None,
        max_frame: int = rpc.DEFAULT_MAX_FRAME,
        frame_deadline: float = 30.0,
        spill_dir: str | None = None,
        status_port: int | None = None,
        stats_interval: float = 30.0,
    ):
        """``client`` is the base fleet client whose TOPOLOGY the gateway
        rides; every tenant gets a ``for_space`` sibling over it (the
        base's own space is never written through the gateway).
        ``admission`` is the optional SHARED gate stacked over every
        tenant bucket; ``spill_dir`` roots per-tenant spill journals
        (``<spill_dir>/<tenant>``; None = spill off, a dark shard sheds
        writes).  ``stats_interval`` budgets the posting-count refresh —
        fleet-wide stats fan-out never runs more than once per interval.
        """
        self._client = client
        self.registry = registry or TenantRegistry()
        self.name = name
        self.admission = admission
        self.spill_dir = spill_dir
        self.stats_interval = float(stats_interval)
        self._status_port = status_port
        self.status_server = None
        self._lock = threading.Lock()
        self._tenants: dict[str, _Tenant] = {}
        self._postings: dict[str, float] = {}
        self._postings_ts = float("-inf")
        self._stats_lock = threading.Lock()
        self._hlock = threading.Lock()
        self._m_req: dict[tuple, object] = {}
        self._m_rej: dict[tuple, object] = {}
        self._m_sec: dict[tuple, object] = {}
        self._gen = None
        self._instrument()
        self.server = rpc.RpcServer(
            {
                "submit_batch": self._h_submit_batch,
                "probe_batch": self._h_probe_batch,
                "query": self._h_query,
                "tenant_status": self._h_tenant_status,
                "wipe_tenant": self._h_wipe_tenant,
            },
            host=host,
            port=port,
            name=name,
            max_frame=max_frame,
            frame_deadline=frame_deadline,
            admission=admission,
            admission_methods=GATED_VERBS,
            admission_resolver=self._resolve_admission,
        )

    # -- instrumentation ---------------------------------------------------

    def _instrument(self) -> None:
        """(Re-)register the gateway-owned series; the admission plane's
        lazy re-instrument pattern guards every handle against a registry
        reset between tests."""
        from advanced_scrapper_tpu.obs import telemetry

        self._m_req.clear()
        self._m_rej.clear()
        self._m_sec.clear()
        self._gen = telemetry.REGISTRY.generation
        # posting counts per tenant key space, from budget-guarded fleet
        # stats (expand: one series per tenant label value)
        telemetry.REGISTRY.gauge_fn(
            "astpu_tenant_postings",
            lambda gw: gw._postings_snapshot(),
            owner=self,
            expand="tenant",
            help="per-tenant key-space posting counts (segments + WAL), "
            "refreshed at most once per stats_interval",
            always=True,
            gateway=self.name,
        )

    def _fresh(self) -> None:
        from advanced_scrapper_tpu.obs import telemetry

        if self._gen != telemetry.REGISTRY.generation:
            with self._hlock:
                if self._gen != telemetry.REGISTRY.generation:
                    self._instrument()

    def _req_counter(self, tenant: str, verb: str, outcome: str):
        self._fresh()
        key = (tenant, verb, outcome)
        c = self._m_req.get(key)
        if c is None:
            from advanced_scrapper_tpu.obs import telemetry

            c = telemetry.REGISTRY.counter(
                "astpu_tenant_requests_total",
                "front-door requests by tenant, verb and outcome "
                "(ok/error/rejected)",
                always=True,
                gateway=self.name,
                tenant=tenant,
                verb=verb,
                outcome=outcome,
            )
            self._m_req[key] = c
        return c

    def _reject_counter(self, tenant: str, reason: str):
        self._fresh()
        key = (tenant, reason)
        c = self._m_rej.get(key)
        if c is None:
            from advanced_scrapper_tpu.obs import telemetry

            c = telemetry.REGISTRY.counter(
                "astpu_tenant_rejected_total",
                "tenant-quota admission refusals by reason (each answered "
                "as a retriable RpcOverloaded with retry-after)",
                always=True,
                gateway=self.name,
                tenant=tenant,
                reason=reason,
            )
            self._m_rej[key] = c
        return c

    def _seconds(self, tenant: str, verb: str):
        self._fresh()
        key = (tenant, verb)
        h = self._m_sec.get(key)
        if h is None:
            from advanced_scrapper_tpu.obs import telemetry

            h = telemetry.REGISTRY.histogram(
                "astpu_tenant_seconds",
                "front-door verb wall clock by tenant (the per-tenant p99 "
                "SLO objective evaluates this series)",
                always=True,
                gateway=self.name,
                tenant=tenant,
                verb=verb,
            )
            self._m_sec[key] = h
        return h

    # -- tenancy -----------------------------------------------------------

    def _ensure(self, tenant: str) -> _Tenant:
        """Resolve (provisioning on first sight when the registry allows)
        one tenant's live state."""
        t = self._tenants.get(tenant)
        if t is not None:
            return t
        spec = self.registry.get(tenant)  # KeyError = unknown/refused
        with self._lock:
            t = self._tenants.get(tenant)
            if t is None:
                ctrl = AdmissionController(
                    rate=spec.rate,
                    burst=spec.burst,
                    max_inflight=spec.max_inflight,
                    name=f"tenant:{tenant}",
                )
                spill = None
                if self.spill_dir:
                    import os

                    spill = os.path.join(self.spill_dir, tenant)
                client = self._client.for_space(
                    tenant_space(tenant), spill_dir=spill
                )
                t = _Tenant(spec, ctrl, client)
                self._tenants[tenant] = t
        return t

    def _tenant_of(self, header: dict) -> _Tenant:
        tid = header.get("tenant")
        if not isinstance(tid, str):
            raise ValueError("request carries no tenant id")
        try:
            return self._ensure(tid)
        except KeyError as e:
            raise ValueError(str(e)) from None

    def _resolve_admission(self, method: str, header: dict):
        """``RpcServer``'s per-request hook: gated verbs resolve to the
        request tenant's own bucket (stacked under the shared gate).
        Malformed/unknown tenants resolve to no gate — the handler
        answers the clean, deterministic error instead of a retriable
        overload."""
        if method not in GATED_VERBS:
            return None
        tid = header.get("tenant")
        if not isinstance(tid, str):
            return None
        try:
            t = self._ensure(tid)
        except (KeyError, ValueError):
            return None
        try:
            prio = int(header.get("priority", PRIORITY_NORMAL))
        except (TypeError, ValueError):
            prio = PRIORITY_NORMAL
        prio = max(PRIORITY_CRITICAL, min(PRIORITY_LOW, prio))
        return _BoundGate(self, t, method), prio

    # -- verbs -------------------------------------------------------------

    def _timed(self, verb: str, header: dict, fn):
        """Shared verb wrapper: resolve tenant, time, count outcome."""
        t = self._tenant_of(header)
        tid = t.spec.tenant
        t0 = time.perf_counter()
        try:
            out = fn(t, header)
            self._req_counter(tid, verb, "ok").inc()
            return out
        except Exception:
            self._req_counter(tid, verb, "error").inc()
            raise
        finally:
            self._seconds(tid, verb).observe(time.perf_counter() - t0)

    def _h_submit_batch(self, header, arrays):
        """Check-and-add one batch of band-key rows for the tenant.
        Arrays: ``[keys (n, bands) u64, ids (n,) u64]`` — or just
        ``[keys]`` with ``allocate: true`` to draw ids from the tenant
        space's durable allocator (returned alongside the attributions).
        Per-row attributions (−1 = first sight) come back as ``int64``;
        verdicts are counted (and, when enabled, journaled with the
        tenant id) through the decision-provenance plane."""

        def run(t: _Tenant, header):
            if len(arrays) == 1 and header.get("allocate"):
                keys = np.ascontiguousarray(arrays[0], np.uint64)
                if keys.ndim != 2:
                    raise ValueError("submit_batch keys must be 2-D")
                ids = t.client.allocate_doc_ids(keys.shape[0])
                allocated = True
            elif len(arrays) == 2:
                keys = np.ascontiguousarray(arrays[0], np.uint64)
                if keys.ndim != 2:
                    raise ValueError("submit_batch keys must be 2-D")
                ids = np.ascontiguousarray(arrays[1], np.uint64).ravel()
                allocated = False
            else:
                raise ValueError(
                    "submit_batch wants [keys, ids] or [keys] + allocate"
                )
            if ids.shape[0] != keys.shape[0]:
                raise ValueError("submit_batch ids/keys length mismatch")
            attr = np.asarray(t.client.check_and_add_batch(keys, ids), np.int64)
            self._record_decisions(t.spec.tenant, ids, attr)
            resp = {"n": int(keys.shape[0]), "allocated": allocated}
            out = [attr]
            if allocated:
                out.append(np.asarray(ids, np.uint64))
            return resp, out

        return self._timed("submit_batch", header, run)

    def _h_probe_batch(self, header, arrays):
        """Read-only attribution of one batch of band-key rows against
        the tenant's space ONLY — a probe under tenant A is structurally
        unable to touch tenant B's postings."""

        def run(t: _Tenant, header):
            (keys,) = arrays
            keys = np.ascontiguousarray(keys, np.uint64)
            if keys.ndim != 2:
                raise ValueError("probe_batch keys must be 2-D")
            attr = t.client.probe_batch(keys)
            return {"n": int(keys.shape[0])}, [np.asarray(attr, np.int64)]

        return self._timed("probe_batch", header, run)

    def _h_query(self, header, arrays):
        """Single-doc point lookup: one row of band keys → the attributed
        doc id (−1 = absent)."""

        def run(t: _Tenant, header):
            (keys,) = arrays
            keys = np.ascontiguousarray(keys, np.uint64).ravel()
            attr = t.client.probe_batch(keys.reshape(1, -1))
            return {"doc": int(np.asarray(attr).ravel()[0])}

        return self._timed("query", header, run)

    def _h_tenant_status(self, header, arrays):
        """The ungated control surface: per-tenant quota/pressure/
        posting-count snapshot (one tenant via the header, or every
        provisioned tenant).  Forces a posting-count refresh inside the
        stats budget."""
        self._refresh_postings()
        tid = header.get("tenant")
        if isinstance(tid, str):
            self._ensure(tid)
        out = {}
        with self._lock:
            items = list(self._tenants.items())
        for name, t in sorted(items):
            if isinstance(tid, str) and name != tid:
                continue
            out[name] = {
                "space": tenant_space(name),
                "rate": t.spec.rate,
                "burst": t.ctrl.burst,
                "max_inflight": t.spec.max_inflight,
                "inflight": t.ctrl.inflight(),
                "pressure": t.ctrl.pressure(),
                "p99_slo_s": t.spec.p99_slo_s,
                "reject_budget": t.spec.reject_budget,
                "postings": self._postings.get(name),
            }
        return {"tenants": out, "declared": list(self.registry.declared())}

    def _h_wipe_tenant(self, header, arrays):
        """Offboarding: drop every posting of the tenant's key space
        fleet-wide (the namespace policy allows wipe under ``tenant:``;
        real spaces stay refused server-side)."""
        t = self._tenant_of(header)
        dropped = t.client.wipe()
        with self._stats_lock:
            self._postings.pop(t.spec.tenant, None)
        return {"dropped": int(dropped)}

    # -- decision provenance ----------------------------------------------

    def _record_decisions(self, tenant: str, ids, attr) -> None:
        """Bill gateway-settled verdicts to the decision plane: the
        fleet's probe→resolve→insert path settles on index evidence, so
        the tier is ``index``; journal rows carry the tenant id (the
        zero-leakage tests join on it)."""
        from advanced_scrapper_tpu.obs import decisions

        rec = decisions.get_recorder()
        a = np.asarray(attr, np.int64)
        dup = int((a >= 0).sum())
        rec.count("index", "dup", dup)
        rec.count("index", "unique", int(a.size - dup))
        if rec.journal is not None and a.size:
            ids = np.asarray(ids, np.uint64)
            rec.journal_rows(
                [
                    {
                        "tier": "index",
                        "verdict": "dup" if int(att) >= 0 else "unique",
                        "doc": int(doc),
                        "attr": int(att),
                        "tenant": tenant,
                    }
                    for doc, att in zip(ids.tolist(), a.tolist())
                ]
            )

    # -- posting counts ----------------------------------------------------

    def _postings_snapshot(self) -> dict[str, float]:
        """The gauge_fn target: last-known per-tenant posting counts.
        Scrapes never block on fleet RPCs — a refresh happens at most
        once per ``stats_interval`` and only when the budget lock is
        free."""
        self._refresh_postings(blocking=False)
        with self._stats_lock:
            return dict(self._postings)

    def _refresh_postings(self, *, blocking: bool = True) -> None:
        now = time.monotonic()
        if now - self._postings_ts < self.stats_interval:
            return
        if not self._stats_lock.acquire(blocking=blocking):
            return
        try:
            if now - self._postings_ts < self.stats_interval:
                return
            self._postings_ts = now
            with self._lock:
                items = list(self._tenants.items())
            for tid, t in items:
                space = tenant_space(tid)
                total = 0
                for st in t.client.stats()["shards"]:
                    sp = (st or {}).get("spaces", {}).get(space)
                    if sp:
                        total += int(sp.get("segment_postings", 0))
                        total += int(sp.get("wal_postings", 0))
                self._postings[tid] = float(total)
        finally:
            self._stats_lock.release()

    # -- SLO + autoscaler feeds -------------------------------------------

    def objectives(self) -> list[dict]:
        """Per-tenant SLO objectives for the PR 11 engine (plain dicts —
        ``SloEngine`` loads them declaratively): a p99 latency ceiling
        over ``astpu_tenant_seconds{tenant=…}`` and a reject-ratio cap of
        ``astpu_tenant_rejected_total`` / ``astpu_tenant_requests_total``,
        each with the tenant's declared error budget."""
        objs = []
        with self._lock:
            items = sorted(self._tenants.items())
        for tid, t in items:
            objs.append(
                {
                    "name": f"tenant_{tid}_p99",
                    "kind": "p99_latency_max",
                    "metric": "astpu_tenant_seconds",
                    "labels": {"tenant": tid},
                    "threshold": t.spec.p99_slo_s,
                    "budget": t.spec.slo_budget,
                }
            )
            objs.append(
                {
                    "name": f"tenant_{tid}_rejects",
                    "kind": "ratio_max",
                    "metric": "astpu_tenant_rejected_total",
                    "denominator": "astpu_tenant_requests_total",
                    "labels": {"tenant": tid},
                    "threshold": t.spec.reject_budget,
                    "budget": t.spec.slo_budget,
                }
            )
        return objs

    def pressure(self) -> float:
        """The gateway's aggregate pressure signal: the max over every
        tenant bucket (each also exports
        ``astpu_admission_pressure{gate="tenant:<id>"}``, which
        ``runtime.autoscaler.admission_pressure()`` folds in fleet-wide
        — this accessor is for direct ``Autoscaler.observe`` wiring)."""
        with self._lock:
            tenants = list(self._tenants.values())
        pressures = [t.ctrl.pressure() for t in tenants]
        if self.admission is not None:
            pressures.append(self.admission.pressure())
        return max(pressures, default=0.0)

    # -- lifecycle ---------------------------------------------------------

    @property
    def port(self) -> int:
        return self.server.port

    def start(self) -> "DedupGateway":
        from advanced_scrapper_tpu.obs import telemetry

        self.server.start()
        if self._status_port is not None or telemetry.enabled():
            self.status_server = telemetry.StatusServer(
                port=self._status_port or 0,
                name=f"gateway-{self.name}",
                extra_status=lambda: {
                    "gateway": self.name,
                    "tenants": self._h_tenant_status({}, [])["tenants"],
                },
            ).start()
        return self

    def stop(self) -> None:
        """Idempotent.  Per-tenant sibling clients are the gateway's own
        and get closed; the BASE client belongs to the caller."""
        self.server.stop()
        if self.status_server is not None:
            self.status_server.stop()
            self.status_server = None
        with self._lock:
            tenants, self._tenants = dict(self._tenants), {}
        for t in tenants.values():
            t.client.close()


def serve_main(argv=None) -> int:
    """Standalone gateway entry
    (``python -m advanced_scrapper_tpu.service.gateway``).

    ``--shard`` declares one fleet shard per flag as comma-separated
    ``host:port`` replicas; the bound gateway port lands in
    ``--port-file`` ATOMICALLY after listen (the shard-server contract,
    so a parent forking the whole stack waits on files, never races the
    bind).  SIGTERM closes cleanly.
    """
    import argparse
    import signal

    ap = argparse.ArgumentParser(description=serve_main.__doc__)
    ap.add_argument(
        "--shard",
        action="append",
        required=True,
        help="one fleet shard: comma-separated host:port replicas "
        "(repeat per shard)",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--port-file", default=None)
    ap.add_argument("--name", default="gateway")
    ap.add_argument(
        "--tenant",
        action="append",
        default=[],
        help="declare one tenant: name[,rate=R][,burst=B][,inflight=N]"
        "[,p99=S][,rejects=F] (repeat per tenant)",
    )
    ap.add_argument(
        "--no-auto-tenants",
        action="store_true",
        help="refuse tenants not declared via --tenant (closed deployment)",
    )
    ap.add_argument(
        "--default-rate", type=float, default=0.0,
        help="token-bucket rate for auto-provisioned tenants (0 = uncapped)",
    )
    ap.add_argument(
        "--default-inflight", type=int, default=16,
        help="concurrency cap for auto-provisioned tenants (0 = uncapped)",
    )
    ap.add_argument(
        "--rate", type=float, default=0.0,
        help="SHARED token-bucket rate over all tenants (0 = none)",
    )
    ap.add_argument(
        "--max-inflight", type=int, default=0,
        help="SHARED concurrency cap over all tenants (0 = none)",
    )
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("--retries", type=int, default=3)
    ap.add_argument("--spill-dir", default=None)
    ap.add_argument(
        "--stats-interval", type=float, default=30.0,
        help="minimum seconds between fleet stats fan-outs for the "
        "per-tenant posting-count gauge",
    )
    ap.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve GET /metrics + /status beside the RPC socket "
        "(0 = ephemeral; omit = only under ASTPU_TELEMETRY)",
    )
    ap.add_argument("--metrics-port-file", default=None)
    args = ap.parse_args(argv)

    if args.metrics_port_file is not None and args.metrics_port is None:
        args.metrics_port = 0

    shards = []
    for spec in args.shard:
        nodes = []
        for hp in spec.split(","):
            host, _, port = hp.strip().rpartition(":")
            nodes.append((host, int(port)))
        shards.append(tuple(nodes))
    client = ShardedIndexClient(
        FleetSpec(shards=tuple(shards)),
        space="bands",
        timeout=args.timeout,
        retries=args.retries,
    )
    registry = TenantRegistry(
        [TenantSpec.parse(t) for t in args.tenant],
        default=TenantSpec(
            tenant="default",
            rate=args.default_rate,
            max_inflight=args.default_inflight,
        ),
        auto_provision=not args.no_auto_tenants,
    )
    admission = None
    if args.rate > 0 or args.max_inflight > 0:
        admission = AdmissionController(
            rate=args.rate,
            max_inflight=args.max_inflight,
            name=args.name,
        )
    gw = DedupGateway(
        client,
        registry=registry,
        host=args.host,
        port=args.port,
        name=args.name,
        admission=admission,
        spill_dir=args.spill_dir,
        status_port=args.metrics_port,
        stats_interval=args.stats_interval,
    ).start()
    if args.port_file:
        from advanced_scrapper_tpu.storage.fsio import atomic_replace

        atomic_replace(args.port_file, str(gw.port).encode())
    if args.metrics_port_file and gw.status_server is not None:
        from advanced_scrapper_tpu.storage.fsio import atomic_replace

        atomic_replace(
            args.metrics_port_file, str(gw.status_server.port).encode()
        )

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_a: stop.set())
    signal.signal(signal.SIGINT, lambda *_a: stop.set())

    # standalone deployments get their SLO verdicts from the gateway
    # process itself: re-load objectives whenever a tenant is provisioned
    # (auto-provision grows the set mid-flight) and evaluate on a slow
    # cadence so /status carries astpu_slo_* for every tenant objective
    slo_engine = None
    n_objectives = -1
    next_eval = 0.0
    try:
        while not stop.is_set():
            time.sleep(0.1)
            if gw.status_server is None:
                continue
            now = time.monotonic()
            if now < next_eval:
                continue
            next_eval = now + 5.0
            objectives = gw.objectives()
            if len(objectives) != n_objectives:
                from advanced_scrapper_tpu.obs.slo import SloEngine

                slo_engine = SloEngine(objectives)
                n_objectives = len(objectives)
            if slo_engine is not None:
                slo_engine.evaluate()
    finally:
        gw.stop()
        client.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(serve_main())
