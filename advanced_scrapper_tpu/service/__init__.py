"""Multi-tenant dedup-as-a-service front door.

The service layer is the last hop before callers: a framed-RPC gateway
(:mod:`.gateway`) that maps every request's tenant id to an isolated
``tenant:<id>:…`` key-space namespace on the index fleet
(:mod:`.tenancy`), stacks per-tenant token buckets on the shared
admission gate, and exports the per-tenant ``astpu_tenant_*`` series the
SLO engine and the autoscaler consume.

Layering: service/ may import net/, index/, runtime/ and obs/ — never
``pipeline``/``ops``/``parallel`` internals (enforced by
``tools/lint_imports.py``): the front door routes and meters, it does
not dedup.
"""

from advanced_scrapper_tpu.service.gateway import DedupGateway, GATED_VERBS
from advanced_scrapper_tpu.service.tenancy import (
    TENANT_ID_RE,
    TenantRegistry,
    TenantSpec,
    tenant_space,
)

__all__ = [
    "DedupGateway",
    "GATED_VERBS",
    "TENANT_ID_RE",
    "TenantRegistry",
    "TenantSpec",
    "tenant_space",
]
