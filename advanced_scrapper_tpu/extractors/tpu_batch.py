"""The TPU batch backend behind the extractor plugin boundary (north star).

BASELINE.json: *"scraped pages are queued into fixed-size batches and
dispatched to a new ``extractors/tpu_batch.py`` that runs byte-tokenization,
MinHash shingling, and LSH near-duplicate bucketing as ``jax.vmap``'d
kernels"*.

:class:`TpuBatchBackend` is a **streaming** dedup stage: extracted article
records are submitted one by one (by the CPU-side fetch loop), buffered into
fixed-size device batches, hashed on the TPU, and joined against a host-side
bucket index that persists across batches — the cross-batch successor of the
reference's resume-by-rereading-CSVs idiom.  Decisions are annotated onto the
records (``dup_of``/``near_dup_of``), never destructive, so downstream
writers decide what to drop.

Division of labour (why the host keeps a dict): the TPU turns O(len) text
into 128-int signatures and 16 band keys — the quadratic/hashing work — while
the host does O(1) dict probes per band key.  A device-resident global index
would need dynamic shapes; a host dict over compact keys is the
XLA-idiomatic split.  For *static* corpora the all-device path
(``parallel.sharded.make_sharded_dedup``) does the whole join on the mesh.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np

from advanced_scrapper_tpu.config import DedupConfig
from advanced_scrapper_tpu.core.hashing import make_params
from advanced_scrapper_tpu.ops.lsh import band_keys_wide, candidate_keys
from advanced_scrapper_tpu.pipeline.dedup import NearDupEngine


# dup marks in bloom stream-index mode: membership is known, the target is
# not (no per-document state exists to attribute against)
BLOOM_SENTINEL = "(bloom)"

# dup marks in persist stream-index mode: the match is attributed to a
# STABLE cross-run doc id (resolvable to a url via the index's docmap
# sidecar, ``PersistentIndex.lookup_names``), not to an in-session key —
# the matched document may have been kept by an earlier process entirely
INDEX_REF_PREFIX = "doc:"


def index_ref(doc_id: int) -> str:
    return f"{INDEX_REF_PREFIX}{int(doc_id)}"


class IndexFingerprintError(ValueError):
    """Stream-index checkpoint written under a different dedup config.

    A distinct type (not a bare ValueError) because the resume path must
    tell it apart from numpy's own ValueErrors on corrupted archives: a
    mismatched config is an operator error that must stay loud, while a
    corrupted file is substrate damage to quarantine and survive."""


def _key_of(rec: dict, field: str) -> str:
    """Single key-normalisation point: missing/None/empty all mean keyless.

    Both stream indexes and both stages share this so their keep/drop
    decisions agree (a record with ``url=None`` must not be a key "None"
    in one stage and keyless in another).
    """
    return str(rec.get(field) or "")


@dataclass
class BatchStats:
    submitted: int = 0
    batches: int = 0
    exact_dups: int = 0
    near_dups: int = 0
    kept: int = 0


class TpuBatchBackend:
    """Streaming exact + near-dup annotator over fixed-size TPU batches."""

    def __init__(
        self,
        cfg: DedupConfig | None = None,
        *,
        text_field: str = "article",
        key_field: str = "url",
        sink: Callable[[dict], None] | None = None,
        exact_stage: bool = True,
        index_dir: str | None = None,
    ):
        """``exact_stage=False`` skips the exact-key dup filter while keys
        stay usable as near-dup targets — for callers whose keys are
        unique BY CONSTRUCTION (e.g. the streaming dedup CLI's line
        numbers).  Load-bearing in bloom mode: inserting millions of
        never-colliding keys into the fixed-size url filter would
        saturate it into false "exact dup" drops.

        ``index_dir`` (persist mode) overrides ``cfg.index_dir`` — the
        directory of the durable log-structured posting index."""
        self.cfg = cfg or DedupConfig()
        self.params = make_params(
            num_perm=self.cfg.num_perm,
            num_bands=self.cfg.num_bands,
            shingle_k=self.cfg.shingle_k,
            seed=self.cfg.seed,
        )
        self.engine = NearDupEngine(self.cfg, self.params)
        self.text_field = text_field
        self.key_field = key_field
        self.sink = sink
        self.exact_stage = exact_stage
        self._buffer: list[dict] = []  # stats live in _reset_stream_state
        # cross-batch state — three interchangeable stream indexes:
        #   exact: attributed dup targets, host memory grows with the stream;
        #   bloom: LSHBloom (utils/bloom.py) — fixed memory forever, dup
        #   marks carry the sentinel BLOOM_SENTINEL instead of a target key;
        #   persist: the index/ subsystem — durable on-disk postings with
        #   bounded resident memory, dup marks carry ``doc:<id>`` references
        #   stable across process restarts (cross-RUN dedup).
        self._bloom_mode = self.cfg.stream_index == "bloom"
        self._persist_mode = self.cfg.stream_index == "persist"
        if self._bloom_mode or self._persist_mode:
            from advanced_scrapper_tpu.utils.bloom import hash_key64, pack_keys64

            self._hash_key64 = hash_key64
            self._pack_keys64 = pack_keys64
        elif self.cfg.stream_index != "exact":
            raise ValueError(
                f"unknown stream_index {self.cfg.stream_index!r}; "
                "use exact|bloom|persist"
            )
        if self._persist_mode:
            self._index_dir = index_dir or self.cfg.index_dir
            if not self._index_dir:
                raise ValueError(
                    "stream_index='persist' needs an index directory "
                    "(cfg.index_dir or the index_dir argument)"
                )
        self._reset_stream_state()
        self._bridge_stats()

    _seq_lock = threading.Lock()
    _seq = 0

    def _bridge_stats(self) -> None:
        """Expose :class:`BatchStats` as scrape-time callback gauges (the
        streaming twin of the scraper's StatsTracker bridge): the stream's
        exact-dup / near-dup / kept accounting reads live on ``/status``
        without the submit path ever touching the registry.  Per-instance
        ``stream=`` label: two live backends (an exact + a bloom stream in
        one process) must not replace each other's series."""
        from advanced_scrapper_tpu.obs import telemetry

        with TpuBatchBackend._seq_lock:
            sid = str(TpuBatchBackend._seq)
            TpuBatchBackend._seq += 1
        for name in ("submitted", "batches", "exact_dups", "near_dups", "kept"):
            telemetry.gauge_fn(
                f"astpu_stream_{name}",
                lambda b, _n=name: getattr(b.stats, _n),
                owner=self,
                help=f"streaming dedup backend cumulative {name}",
                stream=sid,
            )
        telemetry.gauge_fn(
            "astpu_stream_buffered",
            lambda b: len(b._buffer),
            owner=self,
            help="records buffered toward the next device batch",
            stream=sid,
        )
        telemetry.gauge_fn(
            "astpu_stream_index_keys",
            lambda b: (
                b._bloom.inserted
                if b._bloom_mode
                else b._pindex.posting_count()
                if b._persist_mode
                else len(b._kept_keys)
            ),
            owner=self,
            help="cross-batch stream-index population",
            stream=sid,
        )
        if self._bloom_mode:
            # live false-positive drift: the predicted row false-drop rate
            # of both fixed-size filters, next to the per-segment OBSERVED
            # ratio the persist index exports (astpu_index_bloom_observed_fp)
            telemetry.gauge_fn(
                "astpu_stream_bloom_predicted_row_fp",
                lambda b: b._bloom.predicted_row_fp(),
                owner=self,
                help="formula row false-drop rate of the band filters at "
                "the current insert count (utils.bloom saturation math)",
                stream=sid,
                filter="bands",
            )
            telemetry.gauge_fn(
                "astpu_stream_bloom_predicted_row_fp",
                lambda b: b._bloom_urls.predicted_row_fp(),
                owner=self,
                help="formula row false-drop rate of the band filters at "
                "the current insert count (utils.bloom saturation math)",
                stream=sid,
                filter="urls",
            )

    def _reset_stream_state(self) -> None:
        """(Re)initialise every piece of cross-batch stream-index state —
        shared by construction and by the quarantine path, which must
        discard a PARTIALLY restored checkpoint (``load_index`` mutates
        progressively, so a mid-load failure would otherwise leave e.g.
        ``_seen_keys`` populated with no matching signatures, silently
        dropping re-scraped rows as exact dups)."""
        if self._bloom_mode:
            from advanced_scrapper_tpu.utils.bloom import BloomBandIndex

            self._bloom = BloomBandIndex(
                self.cfg.num_bands,
                bits=self.cfg.bloom_bits,
                num_hashes=self.cfg.bloom_hashes,
                seed=self.cfg.seed,
            )
            # exact-url stage as a 1-band filter over a url hash: bounded too
            self._bloom_urls = BloomBandIndex(
                1, bits=self.cfg.bloom_bits, num_hashes=self.cfg.bloom_hashes,
                seed=self.cfg.seed + 1,
            )
            self._bloom_fill_warned = False
        elif self._persist_mode:
            from advanced_scrapper_tpu.index import PersistentIndex

            # a re-reset must not leave two live WAL handles on one dir
            if getattr(self, "_pindex", None) is not None:
                self._pindex.close()
                self._pindex_urls.close()

            # two key domains, two sub-indexes (mirrors bloom mode's two
            # filters): band postings and the exact-url stage.  Doc ids are
            # allocated from the bands index and shared, so every dup mark
            # attributes into one id space.
            if self.cfg.index_fleet:
                # remote fleet (DedupConfig.index_fleet): the same two key
                # spaces live on every IndexShardServer; the local index
                # dir holds only the spill journals for dark-shard
                # degraded mode
                from advanced_scrapper_tpu.index.fleet import open_fleet_index

                self._pindex = open_fleet_index(
                    self.cfg, self._index_dir, space="bands"
                )
                self._pindex_urls = open_fleet_index(
                    self.cfg, self._index_dir, space="urls"
                )
            else:
                self._pindex = PersistentIndex(
                    os.path.join(self._index_dir, "bands"),
                    cut_postings=self.cfg.index_cut_postings,
                    compact_segments=self.cfg.index_compact_segments,
                )
                self._pindex_urls = PersistentIndex(
                    os.path.join(self._index_dir, "urls"),
                    cut_postings=self.cfg.index_cut_postings,
                    compact_segments=self.cfg.index_compact_segments,
                )
            # allocation comes from the bands index but the ids are also
            # posted into the urls sub-index; union the durable floors so
            # a crash before the bands index saw an id durably can never
            # reissue one the urls index (or docmap) already references
            self._pindex.raise_doc_id_floor(self._pindex_urls.doc_id_floor())
        self.stats = BatchStats()
        self._seen_keys: set[str] = set()
        self._buckets: dict[tuple[int, int], int] = {}  # (band, key) -> sig idx
        self._kept_sigs: list[np.ndarray] = []
        self._kept_keys: list[str] = []
        self._kept_coarse: list[np.ndarray] = []  # uint32[nb] coarse keys

    # -- checkpoint/resume -------------------------------------------------

    def _config_fingerprint(self) -> np.ndarray:
        cfg = self.cfg
        return np.array(
            [cfg.num_perm, cfg.num_bands, cfg.shingle_k, cfg.seed,
             cfg.cand_subbands, 1 if self._bloom_mode else 0,
             # bloom geometry: num_hashes changes _positions() without
             # changing any array shape — a mismatch would corrupt
             # membership silently, so it must break the fingerprint
             cfg.bloom_bits, cfg.bloom_hashes],
            dtype=np.int64,
        )

    def save_index(self, path: str, fs=None) -> None:
        """Persist the cross-batch stream-index state (npz).

        The reference resumes every long job from its artifacts (SURVEY
        §5.4: CSV anti-join, shard files, ledger, ``is_scraped``); the
        streaming dedup index is the one piece of long-lived state those
        artifacts cannot rebuild cheaply — without it a restarted scraper
        re-admits near-dups of everything already streamed.  Exact mode
        stores keys + kept signatures (band buckets are a deterministic
        function of the signatures and are rebuilt on load); bloom mode
        stores the filter bit-planes.

        Torn-write safety: the npz is written to a tmp through the
        ``storage.fsio`` seam, flushed AND fsynced, then renamed over the
        target — a crash at any byte leaves the previous checkpoint
        intact (whole-or-previous, never torn).
        """
        if self._buffer:
            raise ValueError(
                "flush() before save_index(): buffered records would be lost"
            )
        if self._persist_mode:
            # the persist index has no whole-state artifact to rewrite —
            # durability is continuous (WAL) — so "save" degrades to the
            # checkpoint cadence work: fsync + due segment cut
            self._pindex.checkpoint()
            self._pindex_urls.checkpoint()
            return
        state: dict = {
            "fingerprint": self._config_fingerprint(),
            "stats": np.array(
                [self.stats.submitted, self.stats.batches, self.stats.exact_dups,
                 self.stats.near_dups, self.stats.kept], dtype=np.int64,
            ),
        }
        if self._bloom_mode:
            for name, idx in (("bloom", self._bloom), ("bloom_urls", self._bloom_urls)):
                for k, v in idx.state().items():
                    state[f"{name}_{k}"] = v
        else:
            state["seen_keys"] = np.array(sorted(self._seen_keys), dtype="U")
            state["kept_keys"] = np.array(self._kept_keys, dtype="U")
            state["kept_sigs"] = (
                np.stack(self._kept_sigs)
                if self._kept_sigs
                else np.zeros((0, self.params.num_perm), np.uint32)
            )
        # atomic commit through the fsio seam: savez streams straight into
        # the tmp handle (no second in-memory copy of a checkpoint that
        # holds every kept signature), then flush+fsync+rename — a crash
        # at any byte leaves the previous checkpoint intact (and savez
        # gets no chance to play ".npz" suffix games with a half-named
        # tmp, since it was handed an open file object)
        from advanced_scrapper_tpu.storage.fsio import atomic_write

        def write_npz(fh):
            # np.savez_compressed's own internals, written out so the
            # archive can be DISARMED on a substrate fault: savez holds
            # its ZipFile privately, and a write failing mid-member
            # leaves that ZipFile unfinalised — its __del__ then retries
            # the end record against the closed tmp handle, logging an
            # "Exception ignored in ZipFile.__del__" traceback on every
            # injected fault
            import zipfile

            from numpy.lib import format as npformat

            zf = zipfile.ZipFile(
                fh, "w", zipfile.ZIP_DEFLATED, allowZip64=True
            )
            try:
                for name, arr in state.items():
                    with zf.open(name + ".npy", "w", force_zip64=True) as m:
                        npformat.write_array(m, np.asanyarray(arr))
                zf.close()
            except BaseException:
                zf.fp = None  # the torn tmp is discarded anyway; stop
                raise         # __del__ from finalising a broken archive

        atomic_write(path, write_npz, fs=fs)

    def load_index_if_valid(self, path: str, fs=None) -> bool:
        """Resume-safe :meth:`load_index`: a checkpoint that is torn or
        unreadable (a pre-hardening crash artifact, a corrupted byte range)
        is quarantined to ``<path>.quarantine-<pid>`` and ``False`` is
        returned — the caller starts from an empty index, which only
        weakens dedup, never loses rows.  A config-fingerprint mismatch
        still raises: that is an operator error, not substrate damage,
        and resuming past it would corrupt membership silently.
        """
        from advanced_scrapper_tpu.storage.fsio import default_fs

        fs = fs or default_fs()
        if self._persist_mode:
            # the persist index opened (and recovered) itself at
            # construction; ``path`` is the LEGACY npz checkpoint location,
            # auto-imported once into the new index (MIGRATION.md)
            return self._import_legacy_npz(path, fs)
        if not fs.exists(path):
            return False
        try:
            self.load_index(path)
            return True
        except IndexFingerprintError:
            raise  # config mismatch — loud by design
        except Exception as e:
            # substrate damage of every flavour: zipfile.BadZipFile,
            # EOFError, KeyError, OSError — and numpy's own ValueErrors on
            # corrupted archives ("Cannot load file containing pickled
            # data...", "EOF: reading array data"), which is why the
            # fingerprint branch above needs its own exception type

            # load_index mutates progressively — discard whatever half of
            # the checkpoint made it in before the corruption was hit
            self._reset_stream_state()
            self._quarantine_ckpt(path, fs, e, "resuming with an empty index")
            return False

    def _quarantine_ckpt(self, path: str, fs, e: Exception, tail: str) -> None:
        """The ONE quarantine contract for an unreadable npz checkpoint
        (resume and legacy-import paths must never diverge): rename aside,
        count, flight-record, explain on stderr."""
        import sys

        quarantine = f"{path}.quarantine-{os.getpid()}"
        try:
            fs.replace(path, quarantine)
        except OSError:
            quarantine = "<unmovable>"
        from advanced_scrapper_tpu.obs import telemetry, trace

        telemetry.event_counter(
            "astpu_quarantine_total",
            "crash artifacts quarantined, by kind",
            kind="stream_index",
        ).inc()
        trace.record(
            "event",
            "quarantine.stream_index",
            path=os.path.basename(path),
            error=str(e),
        )
        print(
            f"tpu_batch: stream-index checkpoint {path} is unreadable "
            f"({e}); quarantined to {quarantine}, {tail}",
            file=sys.stderr,
        )

    def close(self) -> None:
        """Release durable-index handles (persist mode; no-op otherwise)."""
        if self._persist_mode:
            self._pindex.close()
            self._pindex_urls.close()

    def load_index(self, path: str) -> None:
        """Inverse of :meth:`save_index`; the backend must be configured
        identically (enforced via a config fingerprint — a mismatched
        num_perm/banding/seed would corrupt membership silently)."""
        if self._persist_mode:
            raise ValueError(
                "persist mode has no npz checkpoint to load; the index "
                "recovers itself at construction (use load_index_if_valid "
                "for the legacy-npz auto-import)"
            )
        with np.load(path) as data:
            if not np.array_equal(data["fingerprint"], self._config_fingerprint()):
                raise IndexFingerprintError(
                    f"stream-index checkpoint {path} was written under a "
                    "different dedup config (num_perm/bands/k/seed/subbands/"
                    "stream_index/bloom geometry); refusing to resume against it"
                )
            s = data["stats"]
            self.stats = BatchStats(*(int(x) for x in s))
            if self._bloom_mode:
                for name, idx in (
                    ("bloom", self._bloom), ("bloom_urls", self._bloom_urls)
                ):
                    idx.restore(
                        data[f"{name}_words"],
                        int(data[f"{name}_inserted"]),
                        int(data[f"{name}_key_bits"]),
                    )
                return
            self._seen_keys = set(data["seen_keys"].tolist())
            self._kept_keys = [str(k) for k in data["kept_keys"].tolist()]
            sigs = data["kept_sigs"]
            self._kept_sigs = [sigs[i].copy() for i in range(sigs.shape[0])]
        # buckets (and the coarse-key gate rows) are a pure function of the
        # kept signatures: recompute the same candidate keys the insertion
        # path used, first-seen wins
        self._buckets = {}
        self._kept_coarse = []
        if sigs.shape[0]:
            keys = np.asarray(
                candidate_keys(sigs, self.params.band_salt, self.cfg.cand_subbands)
            )
            nb = self.params.num_bands
            for i in range(keys.shape[0]):
                self._kept_coarse.append(keys[i, :nb].copy())
                for b in range(keys.shape[1]):
                    self._buckets.setdefault((b, int(keys[i, b])), i)

    def checkpoint(self, path: str, fs=None) -> None:
        """Persist the stream index at the configured cadence
        (``DedupConfig.ckpt_every_batches``): exact/bloom rewrite the npz
        atomically; persist mode fsyncs the WAL and cuts a due segment —
        incremental, so the cadence can be tight without O(index) rewrites."""
        if self._persist_mode:
            self._pindex.checkpoint()
            self._pindex_urls.checkpoint()
        else:
            self.save_index(path, fs=fs)

    def _import_legacy_npz(self, path: str, fs) -> bool:
        """One-shot migration of a pre-persist npz checkpoint into the
        persistent index: kept signatures re-derive the wide band keys
        (the npz stores the signatures precisely so keys ARE a pure
        function of them), kept urls land in the docmap sidecar, and seen
        urls populate the exact-url sub-index.  The npz is renamed to
        ``<path>.imported`` afterwards so the migration runs once.

        Only exact-mode checkpoints are importable — a bloom checkpoint
        holds no per-document state to attribute or re-key.  An index that
        already has postings skips the import (it already happened, or the
        operator seeded the index deliberately).
        """
        import sys

        if not fs.exists(path):
            return False
        # emptiness probe that holds for BOTH index flavours: the local
        # PersistentIndex and the fleet client (whose stats() is a
        # per-shard list, not the flat dict)
        if self._pindex.doc_id_floor() or self._pindex.posting_count():
            return False  # non-empty index: never double-import
        try:
            with np.load(path) as data:
                fp = data["fingerprint"]
                cfg = self.cfg
                expect = [cfg.num_perm, cfg.num_bands, cfg.shingle_k,
                          cfg.seed, cfg.cand_subbands]
                if [int(x) for x in fp[:5]] != expect:
                    raise IndexFingerprintError(
                        f"legacy checkpoint {path} was written under a "
                        "different dedup config (num_perm/bands/k/seed/"
                        "subbands); refusing to import it"
                    )
                if int(fp[5]) != 0:
                    print(
                        f"tpu_batch: legacy checkpoint {path} is a bloom "
                        "stream index (no per-document state); it cannot "
                        "seed the persistent index — starting empty",
                        file=sys.stderr,
                    )
                    return False
                kept_keys = [str(k) for k in data["kept_keys"].tolist()]
                sigs = np.asarray(data["kept_sigs"])
                seen = [str(k) for k in data["seen_keys"].tolist()]
        except IndexFingerprintError:
            raise  # operator error — loud by design
        except Exception as e:
            # substrate damage: same quarantine contract as the resume path
            self._quarantine_ckpt(
                path, fs, e, "persistent index starts empty"
            )
            return False
        n = len(kept_keys)
        if n:
            ids = self._pindex.allocate_doc_ids(n)
            keys64 = self._pack_keys64(
                np.asarray(band_keys_wide(sigs, self.params.band_salt))
            )
            self._pindex.insert_batch(
                keys64.ravel(), np.repeat(ids, keys64.shape[1])
            )
            self._pindex.log_names(ids.tolist(), kept_keys)
            kept_pos = {k: int(i) for k, i in zip(kept_keys, ids)}
        else:
            kept_pos = {}
        if seen:
            # urls that were seen but not kept (exact/near dups of a kept
            # doc) still mark exact-dup membership; attribute them to the
            # kept doc when the url IS a kept doc's, else to a fresh id
            url_hash = np.array(
                [self._hash_key64(k) for k in seen], dtype=np.uint64
            )
            url_ids = np.empty((len(seen),), np.uint64)
            fresh = [i for i, k in enumerate(seen) if k not in kept_pos]
            for i, k in enumerate(seen):
                if k in kept_pos:
                    url_ids[i] = kept_pos[k]
            if fresh:
                extra = self._pindex.allocate_doc_ids(len(fresh))
                for j, i in enumerate(fresh):
                    url_ids[i] = extra[j]
                # names for the non-kept seen urls too: any doc:<id> an
                # url-dup annotation ever emits must resolve via docmap
                self._pindex.log_names(extra.tolist(), [seen[i] for i in fresh])
            self._pindex_urls.insert_batch(url_hash, url_ids)
        self._pindex.checkpoint()
        self._pindex_urls.checkpoint()
        try:
            fs.replace(path, path + ".imported")
        except OSError:
            pass
        print(
            f"tpu_batch: imported legacy stream-index checkpoint {path} "
            f"({n} kept docs, {len(seen)} seen urls) into {self._index_dir}; "
            f"renamed to {path}.imported",
            file=sys.stderr,
        )
        return True

    # -- submission --------------------------------------------------------

    def submit(self, record: dict) -> list[dict]:
        """Queue one extracted record; returns processed records when a full
        device batch was flushed (empty list otherwise)."""
        self.stats.submitted += 1
        self._buffer.append(record)
        if len(self._buffer) >= self.cfg.batch_size:
            return self._process()
        return []

    def flush(self) -> list[dict]:
        """Process whatever is buffered (padding the device batch)."""
        return self._process() if self._buffer else []

    # -- internals ---------------------------------------------------------

    @staticmethod
    def _decision_recorder():
        from advanced_scrapper_tpu.obs.decisions import get_recorder

        return get_recorder()

    def _process(self) -> list[dict]:
        records, self._buffer = self._buffer, []
        self.stats.batches += 1

        # persist mode: one monotonic doc id per record up front — url
        # postings and band postings of a kept doc must share an id, and
        # ids of records that end up dups are simply never posted under
        # (monotonicity, not density, is the contract)
        doc_ids = (
            self._pindex.allocate_doc_ids(len(records))
            if self._persist_mode
            else None
        )

        # exact stage: host dict over record keys (urls); bloom mode uses a
        # fixed-size 1-band filter over a url hash instead of the growing set
        url_postings = None  # persist mode: deferred (keys, ids, names)
        if not self.exact_stage:
            for rec in records:
                rec["dup_of"] = None
        elif self._persist_mode:
            url_hash = np.array(
                [self._hash_key64(_key_of(rec, self.key_field)) for rec in records],
                dtype=np.uint64,
            )
            keyed = np.array(
                [bool(_key_of(rec, self.key_field)) for rec in records]
            )
            url_attr = np.full(len(records), -1, np.int64)
            if keyed.any():
                # PROBE-only here (cross-run via the durable sub-index,
                # intra-batch via true hash equality); the url postings are
                # inserted AFTER the band postings in _near_dup_persist —
                # a durable url posting with no band postings would make
                # the restarted run skip the record as an exact dup and
                # never re-derive its band keys, blinding the index to its
                # near-dups forever.  (The reverse window — band keys
                # durable, url not — only self-marks the replayed record a
                # near-dup of its earlier incarnation: at-least-once.)
                sub = url_hash[keyed]
                sub_ids = doc_ids[keyed]
                cross = np.asarray(self._pindex_urls.probe_batch(sub))
                _u, first_ix, inverse = np.unique(
                    sub, return_index=True, return_inverse=True
                )
                earlier = first_ix[inverse]
                rows_l = np.arange(sub.size)
                # rows sharing a hash share the cross verdict, so an
                # intra-batch dup of a cross-dup attributes to the SAME
                # prior doc; an intra dup of a fresh row attributes to
                # that (posted) row's id
                url_attr[keyed] = np.where(
                    cross >= 0,
                    cross,
                    np.where(
                        earlier < rows_l,
                        sub_ids[earlier].astype(np.int64),
                        -1,
                    ),
                )
                fresh_sub = np.flatnonzero(url_attr[keyed] < 0)
                keyed_ix = np.flatnonzero(keyed)
                url_postings = (
                    sub[fresh_sub],
                    sub_ids[fresh_sub],
                    [
                        _key_of(records[i], self.key_field)
                        for i in keyed_ix[fresh_sub].tolist()
                    ],
                )
            for i, rec in enumerate(records):
                if url_attr[i] >= 0:
                    rec["dup_of"] = index_ref(url_attr[i])
                    self.stats.exact_dups += 1
                else:
                    rec["dup_of"] = None
            drec = self._decision_recorder()
            n_dup = int((url_attr >= 0).sum())
            drec.count("exact", "dup", n_dup)
            drec.count("exact", "unique", int(keyed.sum()) - n_dup)
            if drec.journal is not None:
                drec.journal_rows(
                    {
                        "doc": int(doc_ids[i]),
                        "name": _key_of(records[i], self.key_field),
                        "verdict": "dup" if url_attr[i] >= 0 else "unique",
                        "tier": "exact",
                        "attr": int(url_attr[i]),
                        "band_key": int(url_hash[i]),
                        "regime": "stream",
                    }
                    for i in np.flatnonzero(keyed).tolist()
                )
        elif self._bloom_mode:
            # 64-bit url hash: a collision here is an unverifiable false
            # "exact dup" drop, so 32-bit (crc32) key width was the dominant
            # error term at stream scale (~n/2³²)
            url_hash = np.array(
                [[self._hash_key64(_key_of(rec, self.key_field))] for rec in records],
                dtype=np.uint64,
            )
            keyed = np.array(
                [bool(_key_of(rec, self.key_field)) for rec in records]
            )
            url_dup = np.zeros(len(records), dtype=bool)
            if keyed.any():
                # cross-batch via the filter, intra-batch via hash equality
                url_dup[keyed] = self._bloom_urls.check_and_add_batch(
                    url_hash[keyed]
                )
            for i, rec in enumerate(records):
                if url_dup[i]:
                    rec["dup_of"] = BLOOM_SENTINEL
                    self.stats.exact_dups += 1
                else:
                    rec["dup_of"] = None
            drec = self._decision_recorder()
            n_dup = int(url_dup.sum())
            drec.count("exact", "dup", n_dup)
            drec.count("exact", "unique", int(keyed.sum()) - n_dup)
        else:
            n_dup = n_uni = 0
            for rec in records:
                key = _key_of(rec, self.key_field)
                if key and key in self._seen_keys:
                    rec["dup_of"] = key
                    self.stats.exact_dups += 1
                    n_dup += 1
                else:
                    rec["dup_of"] = None
                    if key:
                        self._seen_keys.add(key)
                        n_uni += 1
            drec = self._decision_recorder()
            drec.count("exact", "dup", n_dup)
            drec.count("exact", "unique", n_uni)

        # near-dup stage: device signatures + band keys (computed together
        # in the engine's fused epilogue — one dispatch off the
        # device-resident accumulator, no sig D2H→re-H2D bounce), host
        # bucket join
        texts = [str(r.get(self.text_field, "") or "") for r in records]
        thresh = self.cfg.sim_threshold
        if self._bloom_mode or self._persist_mode:
            # wide (2×uint32 → uint64) keys: neither index stores
            # signatures to verify agreement against, so key width IS the
            # false-drop floor
            _sigs, keys_wide = self.engine.signatures_and_keys(
                texts, wide=True, sync_sigs=False
            )  # neither index stores signatures: skip their D2H entirely
            keys64 = self._pack_keys64(keys_wide)
            if self._persist_mode:
                return self._near_dup_persist(
                    records, texts, keys64, doc_ids, url_postings
                )
            return self._near_dup_bloom(records, texts, keys64)
        # Coarse + fine candidate columns — the same key scheme as the
        # certified batch engine (ops.lsh.candidate_keys semantics), so
        # the streaming exact index keeps knee-regime candidacy; every hit
        # still verifies by signature agreement before attribution.  (The
        # bloom mode below stays coarse-band: it cannot verify, and
        # widening its key set would trade its bounded-memory contract for
        # unverifiable drops.)
        sigs, keys = self.engine.signatures_and_keys(texts)
        nd_dup = nd_uni = 0
        for i, rec in enumerate(records):
            rec["near_dup_of"] = None
            if rec["dup_of"] is not None:
                continue  # already an exact dup
            if not _key_of(rec, self.key_field):
                continue  # keyless records cannot be referenced as dup targets
            if len(texts[i].encode("utf-8", "replace")) < self.params.shingle_k:
                continue  # no shingles: never bucket
            candidate = None
            nb = self.params.num_bands
            for b in range(keys.shape[1]):
                idx = self._buckets.get((b, int(keys[i, b])))
                if idx is None:
                    continue
                # per-edge bar, same rule as the batch engine
                # (ops.lsh.fine_edge_thresholds): a fine-band hit with no
                # shared coarse band is outside datasketch's candidacy
                # class and must clear sim_threshold + fine_margin
                bar = thresh
                if b >= nb and not (
                    keys[i, :nb] == self._kept_coarse[idx]
                ).any():
                    bar = thresh + self.cfg.fine_margin
                agree = float(np.mean(self._kept_sigs[idx] == sigs[i]))
                if agree >= bar:
                    candidate = self._kept_keys[idx]
                    break
            if candidate is not None:
                rec["near_dup_of"] = candidate
                self.stats.near_dups += 1
                nd_dup += 1
            else:
                sig_idx = len(self._kept_sigs)
                # copy: a row view would pin the whole batch array forever
                self._kept_sigs.append(sigs[i].copy())
                self._kept_coarse.append(keys[i, :nb].copy())
                self._kept_keys.append(_key_of(rec, self.key_field))
                for b in range(keys.shape[1]):
                    self._buckets.setdefault((b, int(keys[i, b])), sig_idx)
                self.stats.kept += 1
                nd_uni += 1
        # in-memory stream index: verdicts settle on band collision +
        # signature agreement — the "band" tier
        drec = self._decision_recorder()
        drec.count("band", "dup", nd_dup)
        drec.count("band", "unique", nd_uni)

        if self.sink is not None:
            for rec in records:
                self.sink(rec)
        return records

    def _near_dup_bloom(self, records, texts, keys) -> list[dict]:
        """Bounded-memory near-dup stage: LSHBloom membership per band.

        Rows ineligible for bucketing (exact dups, keyless, sub-shingle
        texts) are neither probed nor inserted — same eligibility rules as
        the exact index.  Hits are marked with ``BLOOM_SENTINEL``.
        """
        eligible = np.array(
            [
                rec["dup_of"] is None
                and bool(_key_of(rec, self.key_field))
                and len(texts[i].encode("utf-8", "replace")) >= self.params.shingle_k
                for i, rec in enumerate(records)
            ]
        )
        dup = np.zeros(len(records), dtype=bool)
        if eligible.any():
            dup[eligible] = self._bloom.check_and_add_batch(keys[eligible])
            # O(1) saturation gauge from the insert count (an actual
            # fill_ratio() scan is O(filter bytes) — 1 GiB at 10M-doc
            # sizing — far too hot for a per-batch check).  Keyed on the
            # row false-drop RATE, not bit fill: at the defaults (k=4,
            # 16 bands) 50% bit fill already means ~64% false drops —
            # silent data loss starts orders of magnitude earlier, so the
            # operator cue fires at a 1% predicted row FP.
            if (
                not self._bloom_fill_warned
                and self._bloom.predicted_row_fp() > 0.01
            ):
                self._bloom_fill_warned = True
                import sys

                print(
                    f"tpu_batch: bloom stream index predicted false-drop "
                    f"rate {self._bloom.predicted_row_fp():.2%} after "
                    f"{self._bloom.inserted} docs — rows are being "
                    f"silently dropped as dups; size bloom_bits for the "
                    f"stream (BloomBandIndex.for_capacity)",
                    file=sys.stderr,
                )
        for i, rec in enumerate(records):
            rec["near_dup_of"] = BLOOM_SENTINEL if dup[i] else None
            if dup[i]:
                self.stats.near_dups += 1
            elif eligible[i]:
                self.stats.kept += 1
        # bloom stream index: membership-settled verdicts (no attribution
        # to journal — the filter stores no doc ids)
        drec = self._decision_recorder()
        drec.count("index", "dup", int(dup.sum()))
        drec.count("index", "unique", int(eligible.sum()) - int(dup.sum()))
        if self.sink is not None:
            for rec in records:
                self.sink(rec)
        return records

    def _near_dup_persist(
        self, records, texts, keys, doc_ids, url_postings=None
    ) -> list[dict]:
        """Durable near-dup stage: the persistent posting index decides.

        Same eligibility rules as the other indexes; hits attribute to the
        matched posting's stable doc id (``doc:<id>``) — a document first
        seen three process restarts ago still catches today's near-dups.
        Kept rows post their band keys (WAL-framed, so the decision
        survives any crash after the append); the exact stage's deferred
        url postings land AFTER them (see the ordering note in
        ``_process``), and every url-fresh row's name goes to the docmap
        sidecar so no ``doc:<id>`` annotation is ever unresolvable.
        """
        eligible = np.array(
            [
                rec["dup_of"] is None
                and bool(_key_of(rec, self.key_field))
                and len(texts[i].encode("utf-8", "replace")) >= self.params.shingle_k
                for i, rec in enumerate(records)
            ]
        )
        attr = np.full(len(records), -1, np.int64)
        if eligible.any():
            attr[eligible] = self._pindex.check_and_add_batch(
                keys[eligible], doc_ids[eligible]
            )
        if url_postings is not None:
            u_keys, u_ids, u_names = url_postings
            if u_keys.size:
                self._pindex_urls.insert_batch(u_keys, u_ids)
                self._pindex.log_names(u_ids.tolist(), u_names)
        else:
            # no url stage (exact_stage=False callers): kept rows are the
            # only attribution targets — log their keys here instead
            kept_rows = np.flatnonzero(eligible & (attr < 0))
            if kept_rows.size:
                self._pindex.log_names(
                    doc_ids[kept_rows].tolist(),
                    [
                        _key_of(records[i], self.key_field)
                        for i in kept_rows.tolist()
                    ],
                )
        for i, rec in enumerate(records):
            rec["near_dup_of"] = index_ref(attr[i]) if attr[i] >= 0 else None
            if attr[i] >= 0:
                self.stats.near_dups += 1
            elif eligible[i]:
                self.stats.kept += 1
        self._emit_stream_decisions(records, attr, keys, doc_ids, eligible)
        if self.sink is not None:
            for rec in records:
                self.sink(rec)
        return records

    def _emit_stream_decisions(
        self, records, attr, keys, doc_ids, eligible
    ) -> None:
        """Decision provenance for the persist near-dup stage: every
        eligible row settled at tier "index" (posting hit or fresh post).
        Journal rows carry the row's STABLE doc id and url — the join
        keys ``tools/explain_dedup.py`` resolves against the docmap —
        and dup rows' winning band keys come from a per-key re-probe of
        their own (already-posted) keys: the column whose per-key
        attribution equals the row's answer is the colliding band.  The
        re-probe runs only when the journal is enabled."""
        drec = self._decision_recorder()
        dup_rows = np.flatnonzero(attr >= 0)
        n_dup = int(dup_rows.size)
        drec.count("index", "dup", n_dup)
        drec.count("index", "unique", int(eligible.sum()) - n_dup)
        if drec.journal is None:
            return
        band_keys: dict[int, int | None] = {}
        if n_dup:
            nb = keys.shape[1]
            probed = np.asarray(
                self._pindex.probe_batch(keys[dup_rows].reshape(-1))
            ).reshape(n_dup, nb)
            for x, i in enumerate(dup_rows.tolist()):
                cols = np.flatnonzero(probed[x] == attr[i])
                band_keys[i] = int(keys[i, cols[0]]) if cols.size else None
        drec.journal_rows(
            {
                "doc": int(doc_ids[i]),
                "name": _key_of(records[i], self.key_field),
                "verdict": "dup" if attr[i] >= 0 else "unique",
                "tier": "index",
                "attr": int(attr[i]),
                "band_key": band_keys.get(int(i)),
                "regime": "stream",
            }
            for i in np.flatnonzero(eligible).tolist()
        )
