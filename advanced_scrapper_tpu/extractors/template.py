"""Declarative CSS-selector extraction templates.

The reference's only real config system (SURVEY.md §5.6): ``templates.json``
entries of ``{field: selector | {selector, attribute, index, inner}}``,
registered at runtime (``01_server.py:29-41``) and interpreted recursively by
``extract_elements`` (``03_worker_multi.py:107-133``, ``local.py:61-83``,
``10_btc_articles.py:152-176``).  This module reproduces that dialect
exactly:

- a **plain string** spec is a selector; the first match's stripped text is
  taken, ``''`` when absent (``03_worker_multi.py:140-145``);
- a **dict** spec has ``selector`` (CSS, required), ``attribute`` (default
  ``'text'`` → stripped text, otherwise an HTML attribute,
  ``local.py:63,77-80``), ``index`` (a **list** of element indices, falsy →
  all matches, ``03_worker_multi.py:115-117``) and ``inner`` (a nested
  *spec dict* applied to each selected element, ``local.py:73-75``);
- dict specs always return a **list** (one entry per selected element,
  nested lists for ``inner``); no matches → ``[]``;
- per-field errors degrade to ``''`` rather than failing the page
  (``03_worker_multi.py:148-150``).

``make_template_extractor`` turns a template into a callable satisfying the
``extract_article_data(soup) -> dict`` plugin contract so template-driven
sites plug into the same pipeline as hand-written extractors.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

from advanced_scrapper_tpu.extractors import register


def extract_elements(spec: dict, parent) -> list:
    """Interpret one dict spec against a soup element (reference dialect)."""
    selector = spec["selector"]
    attribute = spec.get("attribute", "text")
    index = spec.get("index")
    inner = spec.get("inner")

    elements = parent.select(selector)
    if not elements:
        return []
    if index:
        elements = [elements[i] for i in index if i < len(elements)]
    values = []
    for el in elements:
        if inner:
            values.append(extract_elements(inner, el))
        elif attribute == "text":
            values.append(el.get_text(strip=True))
        else:
            values.append(el.get(attribute, ""))
    return values


def extract_with_template(soup, template: dict) -> dict:
    """Apply a full ``{field: spec}`` template to a page."""
    out: dict[str, Any] = {}
    for field, spec in template.items():
        try:
            if isinstance(spec, dict):
                out[field] = extract_elements(spec, soup)
            elif isinstance(spec, str):
                el = soup.select_one(spec)
                out[field] = el.get_text(strip=True) if el is not None else ""
            else:
                raise TypeError(
                    f"template spec must be str or dict, got {type(spec)}"
                )
        except TypeError:
            raise
        except Exception:
            out[field] = ""
    return out


def make_template_extractor(template: dict) -> Callable:
    def extract_article_data(soup) -> dict:
        return extract_with_template(soup, template)

    return extract_article_data


class TemplateStore:
    """Persisted named templates (successor of ``templates.json`` +
    ``POST /add_template``, ``01_server.py:13-41``)."""

    def __init__(self, path: str = "templates.json"):
        self.path = path
        self._templates: dict[str, dict] = {}
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                self._templates = json.load(f)

    def add(self, name: str, template: dict) -> None:
        self._templates[name] = template
        with open(self.path, "w", encoding="utf-8") as f:
            json.dump(self._templates, f, indent=2)
        register(name, make_template_extractor(template))

    def get(self, name: str) -> dict:
        return self._templates[name]

    def names(self) -> list[str]:
        return sorted(self._templates)

    def register_all(self) -> None:
        for name, tpl in self._templates.items():
            register(name, make_template_extractor(tpl))
