"""Extractor plugin boundary.

The reference's L2→L3 interface is ``import_module(f"extractors.{website}")``
plus the single-function contract ``extract_article_data(soup) -> dict``
(``constant_rate_scrapper.py:301``, ``extractors/yfin.py:7``).  This package
preserves both: any module here (or any registered callable) exposing
``extract_article_data`` is a site plugin, and the TPU batch backend
(``tpu_batch``) plugs in *behind* this boundary exactly as the north star
mandates.
"""

from __future__ import annotations

from importlib import import_module
from typing import Callable, Protocol

#: the plugin output schema (ref constant_rate_scrapper.py:320-330): every
#: ``extract_article_data`` dict writes these columns plus ``url``.  Defined
#: at the extractor boundary — pipeline AND net both consume them, and
#: ``net/`` must not import ``pipeline/`` (tools/lint_imports.py).
SUCCESS_FIELDS = [
    "url",
    "datetime",
    "ticker_symbols",
    "author",
    "source",
    "source_url",
    "title",
    "article",
]
FAILED_FIELDS = ["url", "error"]

_REGISTRY: dict[str, Callable] = {}


class Extractor(Protocol):
    def __call__(self, soup) -> dict: ...


def register(name: str, fn: Callable) -> None:
    """Register a non-module extractor (e.g. a template-driven one)."""
    _REGISTRY[name] = fn


def load_extractor(website: str) -> Callable:
    """Resolve a site name to its ``extract_article_data`` callable.

    Mirrors the reference's dynamic import
    (``constant_rate_scrapper.py:299-304``) with a registry layered on top
    so declarative-template extractors (``template.py``) can be addressed by
    name too.
    """
    if website in _REGISTRY:
        return _REGISTRY[website]
    mod = import_module(f"advanced_scrapper_tpu.extractors.{website}")
    return mod.extract_article_data
