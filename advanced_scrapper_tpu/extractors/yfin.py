"""Yahoo Finance article extractor.

Behavioural contract re-implemented from the reference plugin
(``/root/reference/extractors/yfin.py:7-163``) — same selectors, same output
fields, same rate-limit sentinels — so downstream CSV schemas and the
rate-limit circuit breaker behave identically:

- ``title``        ``div.cover-title`` text (``:13-17``)
- ``error``        ``"rate_limit_reached"`` when the page is Yahoo's outage/
                   throttle interstitial (``:18-21``)
- ``author``       ``div.byline-attr-author`` text (``:24-28``)
- ``datetime``     first ``<time datetime=...>`` attribute (``:31-35``)
- ``article``      structural walk of ``div.body`` — paragraphs, bullet/
                   numbered lists, tables-as-JSON (``:38-125``)
- ``ticker_symbols`` symbols from ``finance.yahoo.com/quote/...`` hrefs under
                   ``div.body-wrap`` (``:149-163``)
- ``source``/``source_url`` from ``a.subtle-link.fin-size-small``
                   aria-label / href (``:134-145``)

One deliberate divergence: ``ticker_symbols`` preserves first-seen document
order (the reference materialises a ``set``, whose order varies per process
with hash randomisation) — deterministic output is required for stable CSV
golden tests.
"""

from __future__ import annotations

import json
import re

_QUOTE_RE = re.compile(r"https://finance\.yahoo\.com/quote/([^/?]+)")

_RATE_LIMIT_NEEDLES = (
    "Thank you for your patience.",
    "Our engineers are working quickly to resolve the issue.",
)
_EDGE_NOT_FOUND = "Edge: Not Found"

_LIST_TAGS = ("ul", "ol")


def _text(el) -> str:
    return el.get_text(strip=True)


def _table_to_json(table) -> str | None:
    rows = table.find_all("tr")
    if not rows:
        return None
    headers = [_text(c) for c in rows[0].find_all(["th", "td"])]
    data_rows = rows[1:] if any(headers) else rows
    if not any(headers):
        headers = []
    out = []
    for row in data_rows:
        cells = [_text(c) for c in row.find_all(["th", "td"])]
        out.append(dict(zip(headers, cells)) if headers and len(headers) == len(cells) else cells)
    return json.dumps(out)


def _walk_body(el, parts: list[str]) -> None:
    name = getattr(el, "name", None)
    if name == "p":
        t = _text(el)
        if t:
            parts.append(t)
    elif name in _LIST_TAGS:
        ordered = name == "ol"
        for idx, li in enumerate(el.find_all("li", recursive=False), 1):
            t = _text(li)
            if t:
                parts.append(f"{idx}. {t}" if ordered else f"• {t}")
    elif name == "li":
        t = _text(el)
        if t:
            parts.append(f"• {t}")
    elif name == "table":
        tj = _table_to_json(el)
        if tj:
            parts.append(tj)
    else:
        for child in el.contents:
            if not isinstance(child, str):
                _walk_body(child, parts)


def _is_rate_limited(soup) -> bool:
    page_text = soup.get_text()
    return (
        all(n in page_text for n in _RATE_LIMIT_NEEDLES)
        or _EDGE_NOT_FOUND in page_text
    )


def extract_ticker_symbols(soup) -> list[str]:
    section = soup.select_one("div.body-wrap")
    if section is None:
        return []
    seen: dict[str, None] = {}
    for link in section.find_all("a", href=True):
        m = _QUOTE_RE.search(link["href"])
        if m:
            seen.setdefault(m.group(1))
    return list(seen)


def extract_article_data(soup) -> dict:
    data: dict = {}

    title_el = soup.select_one("div.cover-title")
    if title_el is not None:
        data["title"] = _text(title_el)
    else:
        data["title"] = ""
        if _is_rate_limited(soup):
            data["error"] = "rate_limit_reached"

    author_el = soup.select_one("div.byline-attr-author")
    data["author"] = _text(author_el) if author_el is not None else ""

    time_el = soup.find("time")
    data["datetime"] = (
        time_el["datetime"] if time_el is not None and time_el.has_attr("datetime") else ""
    )

    body_el = soup.select_one("div.body")
    if body_el is not None:
        parts: list[str] = []
        _walk_body(body_el, parts)
        data["article"] = "\n".join(parts)
    else:
        data["article"] = ""

    data["ticker_symbols"] = extract_ticker_symbols(soup)

    source_el = soup.select_one("a.subtle-link.fin-size-small")
    data["source"] = (
        source_el["aria-label"]
        if source_el is not None and source_el.has_attr("aria-label")
        else ""
    )
    data["source_url"] = (
        source_el["href"] if source_el is not None and source_el.has_attr("href") else ""
    )
    return data
