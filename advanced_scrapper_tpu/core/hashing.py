"""Hash-family parameters for the MinHash kernels and their CPU oracle.

Two parameter sets are derived from one seed:

- **Device family** (``MinHashParams.a32/b32``): 32-bit multiply-add
  permutations ``h_i(x) = a_i * x + b_i (mod 2**32)`` with odd ``a_i``.
  uint32 wrap-around multiply is native on TPU vector lanes; no 61-bit
  arithmetic needed.
- **Oracle family** (``MinHashParams.a61/b61``): datasketch's exact family
  ``h_i(x) = ((a_i * x + b_i) mod (2**61 - 1)) & 0xFFFFFFFF`` with
  ``a_i, b_i`` drawn from ``np.random.RandomState(seed)`` the same way
  datasketch does, so the CPU oracle in ``cpu/oracle.py`` is
  permutation-for-permutation identical to datasketch's MinHash.

Near-dup *recall* is measured pair-wise (did both engines flag the pair),
not signature-wise, so the two families only need to agree statistically on
Jaccard estimation — which any pairwise-independent family does.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

MERSENNE_PRIME = np.uint64((1 << 61) - 1)
MAX_HASH = np.uint64((1 << 32) - 1)


@dataclass(frozen=True)
class MinHashParams:
    num_perm: int
    num_bands: int
    shingle_k: int
    seed: int
    # device (32-bit) permutation family
    a32: np.ndarray  # uint32[num_perm], odd
    b32: np.ndarray  # uint32[num_perm]
    # band mixing salts for LSH band-key hashing
    band_salt: np.ndarray  # uint32[num_bands]
    # oracle (datasketch) permutation family
    a61: np.ndarray  # uint64[num_perm] in [1, p)
    b61: np.ndarray  # uint64[num_perm] in [0, p)

    @property
    def rows_per_band(self) -> int:
        return self.num_perm // self.num_bands


def make_params(
    num_perm: int = 128,
    num_bands: int = 16,
    shingle_k: int = 5,
    seed: int = 1,
) -> MinHashParams:
    if num_perm % num_bands:
        raise ValueError(f"num_perm {num_perm} not divisible by bands {num_bands}")
    # Oracle family: exactly datasketch's generator — interleaved (a_i, b_i)
    # pair draws from one RandomState, matching _init_permutations order.
    gen = np.random.RandomState(seed)
    pairs = [
        (
            gen.randint(1, int(MERSENNE_PRIME), dtype=np.uint64),
            gen.randint(0, int(MERSENNE_PRIME), dtype=np.uint64),
        )
        for _ in range(num_perm)
    ]
    a61 = np.array([p[0] for p in pairs], dtype=np.uint64)
    b61 = np.array([p[1] for p in pairs], dtype=np.uint64)
    # Device family: independent stream so the two families are uncorrelated.
    gen32 = np.random.RandomState((seed + 0x5F3759DF) % (1 << 31))
    a32 = (gen32.randint(0, 1 << 32, size=num_perm, dtype=np.uint64) | 1).astype(
        np.uint32
    )
    b32 = gen32.randint(0, 1 << 32, size=num_perm, dtype=np.uint64).astype(np.uint32)
    band_salt = gen32.randint(1, 1 << 32, size=num_bands, dtype=np.uint64).astype(
        np.uint32
    )
    return MinHashParams(
        num_perm=num_perm,
        num_bands=num_bands,
        shingle_k=shingle_k,
        seed=seed,
        a32=a32,
        b32=b32,
        band_salt=band_salt,
        a61=a61,
        b61=b61,
    )


def gram_hashes_np(raw: bytes, q: int) -> np.ndarray:
    """numpy mirror of ``ops.shingle.shingle_hash`` for host-side name
    hashing: uint32[len(raw)-q+1] (empty when the text is shorter than q).
    Must stay bit-identical to the device kernel — the match screen gathers
    device-built bitmaps at these indices."""
    if len(raw) < q:
        return np.zeros((0,), np.uint32)
    b = np.frombuffer(raw, dtype=np.uint8).astype(np.uint32)
    n = len(raw) - q + 1
    h = np.full(n, 0x811C9DC5, dtype=np.uint32)
    with np.errstate(over="ignore"):
        for j in range(q):
            h = (h ^ b[j : j + n]) * np.uint32(0x01000193)
    return fmix32_np(h)


def fmix32_np(h: np.ndarray) -> np.ndarray:
    """murmur3 32-bit finaliser (numpy mirror of ops.shingle.fmix32)."""
    h = h.astype(np.uint32)
    h ^= h >> np.uint32(16)
    h = (h * np.uint32(0x85EBCA6B)) & MAX_HASH.astype(np.uint32)
    h ^= h >> np.uint32(13)
    h = (h * np.uint32(0xC2B2AE35)) & MAX_HASH.astype(np.uint32)
    h ^= h >> np.uint32(16)
    return h
