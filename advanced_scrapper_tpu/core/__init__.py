from advanced_scrapper_tpu.core.tokenizer import (
    encode_batch,
    encode_blocks,
    bucket_len,
    to_bytes,
)
from advanced_scrapper_tpu.core.hashing import MinHashParams, make_params
from advanced_scrapper_tpu.core.mesh import build_mesh, local_device_count

__all__ = [
    "encode_batch",
    "encode_blocks",
    "bucket_len",
    "to_bytes",
    "MinHashParams",
    "make_params",
    "build_mesh",
    "local_device_count",
]
