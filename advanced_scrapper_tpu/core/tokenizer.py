"""Byte tokenizer: ragged UTF-8 text → fixed-shape ``uint8[batch, block]``.

The reference feeds ragged Python strings through pandas/rapidfuzz
(``match_keywords.py:150-151``, ``yahoo_links_selenium.py:59``); XLA needs
static shapes, so articles become padded byte rows.  Two tricks keep the MXU
fed without recompilation storms (SURVEY.md §7 "ragged text on fixed
shapes"):

- **bucketed padding** — block lengths are rounded up to power-of-two
  buckets so only O(log max_len) distinct shapes are ever compiled;
- **blockwise splitting** — articles longer than the block are split into
  overlapping blocks (overlap ``k-1`` bytes so no k-shingle is lost at a
  boundary); per-block MinHash minima are later combined with ``jnp.minimum``
  (the TPU analogue of the reference's 20k-row chunked streaming,
  ``match_keywords.py:227-230``).

Tokenisation is a pure reshape/pad — there is no vocabulary.  Padding byte is
0x00, which never participates: validity masks come from ``lengths``.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

MIN_BUCKET = 64


def bucket_len(n: int, min_bucket: int = MIN_BUCKET, max_bucket: int | None = None) -> int:
    """Round ``n`` up to a power-of-two bucket (≥ min_bucket)."""
    b = min_bucket
    while b < n:
        b <<= 1
    if max_bucket is not None:
        b = min(b, max_bucket)
    return b


def bucket_widths(
    lens: np.ndarray, min_bucket: int = MIN_BUCKET, max_bucket: int | None = None
) -> np.ndarray:
    """Vectorised :func:`bucket_len` over an int array (one numpy pass
    instead of a per-document Python loop).  ``frexp`` is exact for every
    integer below 2⁵³, so power-of-two inputs land in their own bucket —
    no float-log edge cases."""
    v = np.maximum(np.asarray(lens, dtype=np.int64), 1)
    m, e = np.frexp(v.astype(np.float64))
    # v = m·2^e with m ∈ [0.5, 1): exact power of two ⇔ m == 0.5
    b = np.ldexp(1.0, e - (m == 0.5)).astype(np.int64)
    b = np.maximum(b, min_bucket)
    if max_bucket is not None:
        b = np.minimum(b, max_bucket)
    return b


def tile_rows_options(bs: int, min_rows: int) -> list[int]:
    """Every row count a greedy power-of-two tile chunker can emit for a
    full-tile size ``bs``: the full tile plus the descending
    power-of-two tail chunks (≥ ``min_rows``; the last one zero-pads).
    THE single source of the O(log bs) shape set shared by an encode
    chunker and its prewarm (dedup tiles use ``min_rows=64``, matcher
    screen tiles ``16``) — deriving it twice is how a chunking tune
    silently disjoints the prewarmed set."""
    rows_set = {bs}
    rows = min_rows
    while rows < bs:
        rows_set.add(rows)
        rows *= 2
    return sorted(rows_set)


def to_bytes(text: str | bytes) -> bytes:
    if isinstance(text, bytes):
        return text
    return text.encode("utf-8", errors="replace")


def encode_batch(
    texts: Sequence[str | bytes],
    block_len: int | None = None,
    *,
    min_bucket: int = MIN_BUCKET,
) -> tuple[np.ndarray, np.ndarray]:
    """Encode a batch of texts into ``(tokens uint8[B, L], lengths int32[B])``.

    Texts longer than the chosen block are truncated; use
    :func:`encode_blocks` when full coverage matters (near-dup hashing).
    When ``block_len`` is None a bucketed length is chosen from the longest
    text in the batch.
    """
    raw = [to_bytes(t) for t in texts]
    longest = max((len(r) for r in raw), default=1)
    L = block_len if block_len is not None else bucket_len(max(longest, 1), min_bucket)
    B = len(raw)
    tokens = np.zeros((B, L), dtype=np.uint8)
    lengths = np.zeros((B,), dtype=np.int32)
    for i, r in enumerate(raw):
        n = min(len(r), L)
        tokens[i, :n] = np.frombuffer(r[:n], dtype=np.uint8)
        lengths[i] = n
    return tokens, lengths


def encode_blocks(
    texts: Sequence[str | bytes],
    block_len: int,
    *,
    overlap: int = 4,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Encode texts into overlapping fixed-size blocks.

    Returns ``(tokens uint8[N, block_len], lengths int32[N], owner int32[N])``
    where ``owner[j]`` is the index into ``texts`` of block ``j``.  Blocks
    overlap by ``overlap`` bytes (pass ``k-1`` for k-shingles) so the set of
    shingles over all blocks of a text equals the shingles of the whole text.
    """
    if block_len <= overlap:
        raise ValueError(f"block_len {block_len} must exceed overlap {overlap}")
    raw_docs = [to_bytes(t) for t in texts]
    from advanced_scrapper_tpu.cpu.hostbatch import encode_blocks_native

    native = encode_blocks_native(raw_docs, block_len, overlap)
    if native is not None:
        return native
    stride = block_len - overlap
    tok_rows: list[np.ndarray] = []
    lens: list[int] = []
    owners: list[int] = []
    for i, r in enumerate(raw_docs):
        if not r:
            r = b"\x00"
        pos = 0
        while True:
            chunk = r[pos : pos + block_len]
            row = np.zeros((block_len,), dtype=np.uint8)
            row[: len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
            tok_rows.append(row)
            lens.append(len(chunk))
            owners.append(i)
            if pos + block_len >= len(r):
                break
            pos += stride
    return (
        np.stack(tok_rows),
        np.asarray(lens, dtype=np.int32),
        np.asarray(owners, dtype=np.int32),
    )


def pad_batch_to(
    tokens: np.ndarray, lengths: np.ndarray, batch: int
) -> tuple[np.ndarray, np.ndarray, int]:
    """Pad the leading batch dim up to ``batch`` rows (returns real count)."""
    n = tokens.shape[0]
    if n == batch:
        return tokens, lengths, n
    if n > batch:
        raise ValueError(f"batch {n} exceeds target {batch}")
    pad_t = np.zeros((batch - n,) + tokens.shape[1:], dtype=tokens.dtype)
    pad_l = np.zeros((batch - n,), dtype=lengths.dtype)
    return np.concatenate([tokens, pad_t]), np.concatenate([lengths, pad_l]), n


def iter_batches(
    texts: Iterable[str | bytes], batch_size: int, block_len: int
) -> Iterable[tuple[np.ndarray, np.ndarray, int]]:
    """Yield fixed-shape ``(tokens, lengths, n_valid)`` batches."""
    buf: list[str | bytes] = []
    for t in texts:
        buf.append(t)
        if len(buf) == batch_size:
            tok, ln = encode_batch(buf, block_len)
            yield tok, ln, len(buf)
            buf = []
    if buf:
        tok, ln = encode_batch(buf, block_len)
        tok, ln, n = pad_batch_to(tok, ln, batch_size)
        yield tok, ln, n
