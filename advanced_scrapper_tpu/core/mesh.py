"""Device-mesh construction.

The reference's parallelism is thread pools, process pools and a TCP
master/worker star (SURVEY.md §2.4); the TPU-native equivalents are all
expressed as shardings over one ``jax.sharding.Mesh``:

- ``data`` axis — batch data parallelism (successor of the 16-thread worker
  pool in ``constant_rate_scrapper.py:417-428`` and the round-robin machine
  split in ``experiental/split.py``);
- ``seq``  axis — sequence/block parallelism for long articles (successor of
  the 20k-row chunk streaming in ``match_keywords.py:227-230``): blocks of
  one article live on different devices and their MinHash partial minima are
  combined with a ``psum``-min collective over this axis.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def local_device_count() -> int:
    return len(jax.devices())


def build_mesh(
    data_parallel: int = -1,
    seq_parallel: int = 1,
    *,
    data_axis: str = "data",
    seq_axis: str = "seq",
    devices=None,
) -> Mesh:
    """Build a ``(data, seq)`` mesh.

    ``data_parallel == -1`` consumes all remaining devices.  On a v5e-8 the
    default is an 8×1 mesh; pass ``seq_parallel=2/4/8`` to trade batch
    parallelism for long-article block parallelism.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if seq_parallel < 1 or n % seq_parallel:
        raise ValueError(f"seq_parallel {seq_parallel} must divide device count {n}")
    if data_parallel == -1:
        data_parallel = n // seq_parallel
    if data_parallel * seq_parallel != n:
        raise ValueError(
            f"mesh {data_parallel}x{seq_parallel} != {n} devices available"
        )
    grid = np.array(devs).reshape(data_parallel, seq_parallel)
    return Mesh(grid, (data_axis, seq_axis))


def parse_mesh_shape(spec: str) -> tuple[int, int]:
    """``"2x4"`` → ``(2, 4)`` — the (data, seq) shape of a mesh-sweep
    axis (``tools/sweep_onchip.py --mesh``, ``ASTPU_BENCH_MESH``).  One
    parser so the sweep driver, the bench and operators' notes all mean
    the same thing by ``DxS``."""
    parts = spec.lower().strip().split("x")
    if len(parts) != 2:
        raise ValueError(f"mesh shape {spec!r} is not of the form DxS")
    try:
        dp, sp = int(parts[0]), int(parts[1])
    except ValueError as e:
        raise ValueError(f"mesh shape {spec!r} is not of the form DxS") from e
    if dp < 1 or sp < 1:
        raise ValueError(f"mesh shape {spec!r} must be positive")
    return dp, sp


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` across the API move: newer jax exposes it at the
    top level (``check_vma``), older releases under
    ``jax.experimental.shard_map`` (``check_rep``).  Both flags guard the
    same replication check, disabled here for the same reason everywhere
    this repo shard_maps: the dedup steps return replicated outputs that
    the checker cannot prove replicated through segment/gather resolution.
    One shim so every call site works on either jax — without it, the whole
    sharded path (and its tests) dies with AttributeError on jax ≤ 0.4.x.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


_compile_cache_dir: str | None = None
_compile_cache_applied = False


def maybe_enable_compile_cache() -> str | None:
    """Point XLA's persistent compilation cache at ``$ASTPU_COMPILE_CACHE``.

    Every cold process used to recompile the dedup tile-shape set from
    scratch (O(log bs) shapes per width bucket — seconds of first-corpus
    latency that bench rounds kept re-paying).  With the knob set, jitted
    programs persist to the named directory and later processes load them
    instead of recompiling; the entry-size/compile-time thresholds are
    dropped to zero so the small minhash steps actually qualify.  Called
    from engine init (``pipeline.dedup.NearDupEngine``) and ``bench.py``;
    idempotent, returns the cache dir when active, None when the knob is
    unset or this jax predates the config names.
    """
    global _compile_cache_dir, _compile_cache_applied
    if _compile_cache_applied:
        return _compile_cache_dir
    import os

    d = os.environ.get("ASTPU_COMPILE_CACHE")
    if not d:
        # do NOT latch: the knob may be exported later in the process
        # (long-lived workers, tests) and must still take effect then
        return None
    _compile_cache_applied = True
    # all-or-nothing: applying the cache dir but not the thresholds would
    # leave the cache writing with defaults that skip every small tile
    # step — enabled-but-useless, while this function reports None.  So
    # every update is staged with its previous value and the whole set
    # rolls back if any config name is missing (older jax).
    updates = (
        ("jax_compilation_cache_dir", d),
        # without these the cache skips "cheap" compiles — which is every
        # tile-step variant on CPU, making the knob silently useless
        ("jax_persistent_cache_min_entry_size_bytes", -1),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
    )
    applied: list[tuple[str, object]] = []
    try:
        for name, value in updates:
            applied.append((name, getattr(jax.config, name)))
            jax.config.update(name, value)
    except Exception:  # older jax without the persistent-cache config
        for name, prev in applied:
            try:
                jax.config.update(name, prev)
            except Exception:  # pragma: no cover - rollback is best-effort
                pass
        return None
    _compile_cache_dir = d
    return d


def auto_h2d_workers() -> int:
    """Default H2D-overlap thread count for the attached transport.

    The tunneled dev chip (plugin platform ``axon``) serializes every
    ``device_put`` into its own round trip (measured r2-r3, DESIGN.md §5);
    overlapping puts from a few threads is the engineered response.  Local
    backends (cpu, pcie-attached tpu) measured fastest with a single put
    thread — extra threads only add handoff overhead when puts don't
    serialize.  Config fields treat 0/None as "auto", resolved here, so
    production defaults and bench defaults CANNOT diverge by transport.
    """
    return 4 if jax.devices()[0].platform == "axon" else 1
