"""Bounded-memory streaming LSH index: per-band Bloom filters.

The default streaming index (``extractors/tpu_batch.py``) stores every kept
document's signature and 16 band keys on the host — ~1 KB per kept document,
unbounded over an unbounded stream (the reference's live pollers,
``experiental/04..10``, run forever).  The LSHBloom construction (Khan et
al., arXiv:2411.04257) replaces the key→doc dict with one Bloom filter per
LSH band: membership of a band key marks a near-duplicate, memory is fixed
at ``num_bands × bits/8`` bytes forever, and the false-positive rate is set
by the filter sizing instead of growing with the corpus.

Trade-offs vs the exact index (both are first-class; pick per workload):

- **no attribution** — a Bloom hit says "a previously seen document shared
  this band", not *which* one, and no stored signature exists to verify
  agreement against.  The false-drop rate has TWO terms: the filter term
  — per band ``ε_band = (1 - e^(-k·n/m))^k``, per ROW (any of ``nb``
  bands hitting) ``ε_row = 1 - (1 - ε_band)^nb ≈ nb·ε_band`` — **and the
  band-key collision rate** ``ε_key ≈ n·num_bands/2^bits(key)`` —
  unverifiable here precisely because nothing is stored.  With 32-bit
  keys ε_key dominates (~4% of unique docs silently dropped at 10M); this
  index therefore expects **uint64 keys** (``ops.lsh.band_keys_wide`` +
  :func:`pack_keys64`), where ε_key ≈ 1e-11 at 10M and the filter term
  dominates.  uint32 keys are still accepted for small/bounded streams.
- **capacity is a sizing decision, not a free lunch** — a Bloom filter
  saturates: at the default 2²⁴ bits/band (k=4, 16 bands, 32 MiB total)
  the MEASURED row false-drop rate is ~3e-3 at 500k kept docs, ~28% at
  2M, and ~100% by 10M (saturated filters) — measured by
  ``tools/soak_bloom.py`` (numbers in DESIGN.md), tracking the formula
  above to within a few % at every checkpoint.  For a target stream size use
  :meth:`BloomBandIndex.for_capacity`, which inverts the formula
  (e.g. 10M kept docs at ε_row ≤ 1e-3 → 2²⁹ bits/band, 1 GiB total).
  :meth:`fill_ratio` is the runtime saturation gauge; the streaming
  backend warns once :meth:`predicted_row_fp` crosses 1% (rate-keyed —
  at the defaults 50% bit fill would already be ~64% false drops).
- **bounded memory** — fixed at construction (32 MiB at defaults), forever.
- **mergeable** — Bloom filters combine with bitwise OR, so per-shard /
  per-host indexes union exactly (the collective analogue of the band-key
  ``psum`` merge in ``parallel/sharded.py``).

Within a batch the filter alone cannot order insertions, so the batch probe
uses *true key equality* intra-batch (first-seen wins, exactly) and the
filters only across batches — stream semantics match the exact index.
"""

from __future__ import annotations

import hashlib

import numpy as np

_MIX_A = np.uint64(0xBF58476D1CE4E5B9)
_MIX_B = np.uint64(0x94D049BB133111EB)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = (x + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    x = ((x ^ (x >> np.uint64(30))) * _MIX_A) & np.uint64(0xFFFFFFFFFFFFFFFF)
    x = ((x ^ (x >> np.uint64(27))) * _MIX_B) & np.uint64(0xFFFFFFFFFFFFFFFF)
    return x ^ (x >> np.uint64(31))


def pack_keys64(wide: np.ndarray) -> np.ndarray:
    """``uint32[..., 2]`` (``ops.lsh.band_keys_wide`` layout) → ``uint64[...]``.

    TPUs have no native uint64, so the two 32-bit lanes are computed on
    device and packed here on host."""
    wide = np.asarray(wide)
    if wide.shape[-1] != 2:
        raise ValueError(f"expected trailing lane dim of 2, got {wide.shape}")
    lo = wide[..., 0].astype(np.uint64)
    hi = wide[..., 1].astype(np.uint64)
    return (hi << np.uint64(32)) | lo


def hash_key64(key: str | bytes) -> int:
    """Stable 64-bit hash of a record key (url) — the exact-dup filter's
    key path.  blake2b-8: keyed-collision rate ~n/2⁶⁴ vs crc32's n/2³²."""
    data = key if isinstance(key, bytes) else key.encode("utf-8", "replace")
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "little")


class BloomBandIndex:
    """One Bloom filter per LSH band over uint64 (preferred) or uint32 keys.

    ``bits`` must be a power of two.  All batch operations are vectorised
    numpy; nothing grows with the stream.
    """

    def __init__(
        self,
        num_bands: int,
        *,
        bits: int = 1 << 24,
        num_hashes: int = 4,
        seed: int = 0,
    ):
        if bits & (bits - 1):
            raise ValueError(f"bits must be a power of two, got {bits}")
        self.num_bands = num_bands
        self.bits = bits
        self.num_hashes = num_hashes
        self.seed = seed
        self._words = np.zeros((num_bands, bits // 64), dtype=np.uint64)
        self.inserted = 0
        # key width is pinned by the FIRST batch: a uint32 key and the same
        # band content's uint64 key hash to different positions, so mixing
        # widths silently corrupts membership — fail loudly instead
        self.key_bits: int | None = None

    @classmethod
    def for_capacity(
        cls,
        capacity: int,
        *,
        num_bands: int = 16,
        row_fp: float = 1e-3,
        num_hashes: int = 4,
        seed: int = 0,
    ) -> "BloomBandIndex":
        """Size the filters for ``capacity`` kept documents at a row-level
        false-drop rate ≤ ``row_fp`` (inverts the saturation math in the
        module docstring — measured to track it in ``tools/soak_bloom.py``).

        Sizing, not magic: 10M docs at ε_row ≤ 1e-3 costs 2²⁹ bits/band
        (1 GiB for 16 bands).  Memory stays fixed at that size forever.
        """
        import math

        if capacity <= 0:
            raise ValueError("capacity must be positive")
        if not 0 < row_fp < 1:
            raise ValueError("row_fp must be in (0, 1)")
        eps_band = 1.0 - (1.0 - row_fp) ** (1.0 / num_bands)
        k = num_hashes
        denom = -math.log(1.0 - eps_band ** (1.0 / k))
        bits = 1 << max(10, math.ceil(math.log2(k * capacity / denom)))
        return cls(num_bands, bits=bits, num_hashes=num_hashes, seed=seed)

    def predicted_row_fp(self, n: int | None = None) -> float:
        """Formula row-level false-drop rate after ``n`` insertions
        (default: what this index has actually inserted)."""
        import math

        n = self.inserted if n is None else n
        eps_band = (1.0 - math.exp(-self.num_hashes * n / self.bits)) ** (
            self.num_hashes
        )
        return 1.0 - (1.0 - eps_band) ** self.num_bands

    # -- core --------------------------------------------------------------

    def _check_width(self, keys: np.ndarray) -> None:
        w = 64 if keys.dtype == np.uint64 else 32
        if self.key_bits is None:
            self.key_bits = w
        elif self.key_bits != w:
            raise ValueError(
                f"index was keyed with {self.key_bits}-bit keys; got "
                f"{keys.dtype} — mixed widths never match each other"
            )

    def _positions(self, keys: np.ndarray) -> np.ndarray:
        """uint64[B, nb, k] bit positions for ``uint{32,64}[B, nb]`` keys."""
        B, nb = keys.shape
        # full-width per-band tweak (splitmix of band index) so 64-bit key
        # entropy survives the band separation; a shifted-constant XOR would
        # collide with the key's high lane
        band_tweak = _splitmix64(
            np.arange(nb, dtype=np.uint64) + np.uint64(self.seed + 1)
        )
        base = keys.astype(np.uint64) ^ band_tweak[None, :]
        hs = np.stack(
            [
                _splitmix64(base + (np.uint64(h) << np.uint64(56)))
                for h in range(self.num_hashes)
            ],
            axis=-1,
        )
        return hs & np.uint64(self.bits - 1)

    def contains_batch(self, keys: np.ndarray) -> np.ndarray:
        """bool[B]: any band of the row fully present in that band's filter."""
        keys = np.asarray(keys)
        self._check_width(keys)
        pos = self._positions(keys)
        word = (pos >> np.uint64(6)).astype(np.int64)
        bit = np.uint64(1) << (pos & np.uint64(63))
        nb = self.num_bands
        band_ix = np.arange(nb)[None, :, None]
        present = (self._words[band_ix, word] & bit) != 0
        return present.all(axis=2).any(axis=1)

    def add_batch(self, keys: np.ndarray, mask: np.ndarray | None = None) -> None:
        """Insert rows (optionally only where ``mask``) into every band filter."""
        keys = np.asarray(keys)
        self._check_width(keys)
        if mask is not None:
            keys = keys[np.asarray(mask, dtype=bool)]
        if keys.size == 0:
            return
        pos = self._positions(keys)
        word = (pos >> np.uint64(6)).astype(np.int64)
        bit = np.uint64(1) << (pos & np.uint64(63))
        band_ix = np.broadcast_to(
            np.arange(self.num_bands)[None, :, None], word.shape
        )
        np.bitwise_or.at(self._words, (band_ix.ravel(), word.ravel()), bit.ravel())
        self.inserted += keys.shape[0]

    def check_and_add_batch(self, keys: np.ndarray) -> np.ndarray:
        """Stream step: ``dup[B]`` per row, then insert the non-dup rows.

        Cross-batch membership via the filters; intra-batch via true key
        equality (vectorised first-occurrence per band) — so a batch of
        identical documents yields one kept row, like the exact index.
        Intra-batch matching is against *any* earlier row sharing the band
        key, including rows themselves marked duplicate — marginally more
        conservative than the exact index (which only matches kept rows);
        a Bloom index cannot attribute representatives anyway.
        """
        keys = np.asarray(keys)
        dup = self.contains_batch(keys)
        B, nb = keys.shape
        rows = np.arange(B)
        for b in range(nb):
            _, first_ix, inverse = np.unique(
                keys[:, b], return_index=True, return_inverse=True
            )
            dup |= first_ix[inverse] < rows
        self.add_batch(keys, mask=~dup)
        return dup

    # -- distribution ------------------------------------------------------

    def merge(self, other: "BloomBandIndex") -> None:
        """Exact union: bitwise OR (the cross-shard/cross-host merge)."""
        if (self.bits, self.num_bands, self.num_hashes, self.seed) != (
            other.bits,
            other.num_bands,
            other.num_hashes,
            other.seed,
        ):
            raise ValueError("cannot merge differently-configured indexes")
        if (
            self.key_bits is not None
            and other.key_bits is not None
            and self.key_bits != other.key_bits
        ):
            raise ValueError(
                f"cannot merge a {self.key_bits}-bit-keyed index with a "
                f"{other.key_bits}-bit one — their keys never match"
            )
        if self.key_bits is None:
            self.key_bits = other.key_bits
        np.bitwise_or(self._words, other._words, out=self._words)
        self.inserted += other.inserted

    def state(self) -> dict:
        """Arrays/scalars that fully reconstruct membership — for
        checkpointing the stream index across process restarts."""
        return {
            "words": self._words,
            "inserted": np.int64(self.inserted),
            "key_bits": np.int64(self.key_bits if self.key_bits is not None else -1),
        }

    def restore(self, words: np.ndarray, inserted: int, key_bits: int) -> None:
        """Inverse of :meth:`state`; the index must be constructed with the
        same (num_bands, bits, num_hashes, seed) — hash positions depend on
        all four, so mismatched params would corrupt membership silently."""
        if words.shape != self._words.shape or words.dtype != np.uint64:
            raise ValueError(
                f"checkpoint shape {words.shape}/{words.dtype} does not match "
                f"this index ({self._words.shape}); was it saved with the "
                "same bits/num_bands config?"
            )
        self._words[...] = words
        self.inserted = int(inserted)
        self.key_bits = None if int(key_bits) < 0 else int(key_bits)

    @property
    def memory_bytes(self) -> int:
        return self._words.nbytes

    def fill_ratio(self) -> float:
        """Fraction of set bits (FP rate grows as this approaches 1)."""
        return float(np.unpackbits(self._words.view(np.uint8)).mean())
