from advanced_scrapper_tpu.utils.setops import (
    anti_join_csv,
    round_robin_split,
    new_links,
)

__all__ = ["anti_join_csv", "round_robin_split", "new_links"]
