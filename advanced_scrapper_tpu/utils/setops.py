"""Dataset set-algebra utilities.

Re-implements the reference's CSV maintenance trio (``experiental/drop.py``,
``new_links.py``, ``split.py`` — SURVEY.md §2.2 E14):

- :func:`anti_join_csv` — drop urls already present in other CSVs
  (``drop.py:1-11``, ``new_links.py:23-35``);
- :func:`round_robin_split` — split a URL list into N worker shards
  round-robin, after pre-dropping done urls (``split.py:10-31``) — the
  reference's manual multi-machine data parallelism;
- :func:`new_links` — write the anti-join result to a new CSV.

Membership checks are host-side set lookups (the done-URL sets are read
via ``storage.csvio.scraped_url_set``); corpus-internal dedup of article
bodies lives in :class:`pipeline.dedup.ExactDedup`, which these utilities
do NOT route through.
"""

from __future__ import annotations

import pandas as pd

from advanced_scrapper_tpu.storage.csvio import scraped_url_set


def anti_join_csv(
    input_csv: str, *done_csvs: str, column: str = "url"
) -> pd.DataFrame:
    """Rows of ``input_csv`` whose url is in none of ``done_csvs``.

    ``repair=False``: the done CSVs arrive on the CLI and may be
    hand-maintained, so they are read leniently and never mutated (the
    torn-tail quarantine is only correct for framework-owned append
    artifacts — ``storage/csvio.py``).  A torn done row parses to a
    partial url here, which errs toward re-queueing that url: duplicate
    work on resume, never a silently dropped one.
    """
    df = pd.read_csv(input_csv)
    done = scraped_url_set(*done_csvs, column=column, repair=False)
    return df[~df[column].astype(str).isin(done)]


def new_links(
    input_csv: str, output_csv: str, *done_csvs: str, column: str = "url"
) -> int:
    out = anti_join_csv(input_csv, *done_csvs, column=column)
    out.to_csv(output_csv, index=False)
    return len(out)


def round_robin_split(
    input_csv: str,
    n_parts: int,
    *done_csvs: str,
    column: str = "url",
    output_template: str = "part_{i}.csv",
) -> list[str]:
    """Round-robin shard split with pre-drop (ref split.py:18-28).

    Returns the written paths; shard i gets rows i, i+n, i+2n, … of the
    remaining work list, preserving order within each shard.
    """
    if n_parts < 1:
        raise ValueError("n_parts must be >= 1")
    if n_parts > 1 and output_template.format(i=0) == output_template.format(i=1):
        raise ValueError(
            f"output_template {output_template!r} has no '{{i}}' placeholder — "
            "all shards would overwrite the same file"
        )
    df = anti_join_csv(input_csv, *done_csvs, column=column).reset_index(drop=True)
    paths = []
    for i in range(n_parts):
        part = df.iloc[i::n_parts]
        path = output_template.format(i=i)
        part.to_csv(path, index=False)
        paths.append(path)
    return paths
