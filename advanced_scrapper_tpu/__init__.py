"""advanced_scrapper_tpu — a TPU-native framework with the capabilities of
``lwowlwowl/advanced_scrapper``.

Layer map (successor of the reference's five de-facto layers, SURVEY.md §1):

- ``core``       device runtime: byte tokenizer, batch specs, mesh builders
- ``ops``        JAX/Pallas kernels: shingling, MinHash, LSH, exact-hash,
                 entity-match screening
- ``parallel``   pjit/shard_map sharding, psum bucket merge, multi-host init,
                 host feed scheduler
- ``cpu``        CPU reference oracles (datasketch-parity MinHash,
                 rapidfuzz-parity partial_ratio) with C++ native backends
- ``extractors`` the reference's plugin boundary: ``extract_article_data(soup)
                 -> dict`` plus the declarative template interpreter and the
                 TPU batch backend (north star)
- ``pipeline``   the four workloads: CDX harvest, constant-rate scrape,
                 Wikidata enrichment, ticker→article matching
- ``storage``    resumable CSV stores, link DBs, progress ledgers
- ``net``        fetch transports and the TCP lease protocol
- ``obs``        windowed stats, console mux, profiler hooks
"""

from advanced_scrapper_tpu.config import Config, default_config

__version__ = "0.5.0"

__all__ = ["Config", "default_config", "__version__"]
