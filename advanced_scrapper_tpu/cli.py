"""Command-line interface.

The reference has no CLI at all — every knob is a module constant and every
workload a hand-run script (SURVEY.md §5.6).  Here each pipeline is an
``astpu`` subcommand; flags override ``ASTPU_*`` env vars which override the
reference-derived defaults in ``config.py``.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import sys

from advanced_scrapper_tpu import __version__, default_config


def _cmd_version(args: argparse.Namespace) -> int:
    print(__version__)
    return 0


def _cmd_config(args: argparse.Namespace) -> int:
    import dataclasses

    print(json.dumps(dataclasses.asdict(default_config()), indent=2, default=str))
    return 0


def _cmd_dedup(args: argparse.Namespace) -> int:
    """Near-dup dedup of a newline-delimited text file (one doc per line)."""

    def open_sink():
        # opened only after the input is readable: creating it earlier
        # would truncate a pre-existing output on any early failure
        return (
            open(args.output, "w", encoding="utf-8")
            if args.output
            else contextlib.nullcontext(sys.stdout)
        )

    if getattr(args, "index", None) and not getattr(args, "stream", False):
        print("astpu dedup: --index requires --stream", file=sys.stderr)
        return 2
    if getattr(args, "stream", False):
        # bounded-memory path: lines flow through the streaming batch
        # backend (cross-batch stream index) instead of being read whole —
        # the corpus never has to fit in host memory, and --index bloom
        # fixes the index size forever (utils/bloom.py)
        from advanced_scrapper_tpu.extractors.tpu_batch import TpuBatchBackend

        cfg = _with_overrides(
            default_config().dedup,
            backend=args.backend,
            stream_index=getattr(args, "index", None),
        )
        kept = total = 0
        with open(args.input, "r", encoding="utf-8", errors="replace") as f, (
            open_sink()
        ) as out:

            def emit(rec: dict) -> None:
                nonlocal kept
                if rec.get("dup_of") is None and rec.get("near_dup_of") is None:
                    kept += 1
                    out.write(rec["article"] + "\n")

            # line-number keys are unique by construction: they make every
            # line a referenceable near-dup target, and exact_stage=False
            # keeps them OUT of the exact-key filter (in bloom mode they
            # would saturate it into false drops at stream scale)
            backend = TpuBatchBackend(cfg, sink=emit, exact_stage=False)
            # Lines shorter than shingle_k can't produce a single shingle,
            # so the near-dup stage passes them through untouched; with
            # exact_stage=False that would keep every copy of e.g. a blank
            # line — diverging from the whole-corpus path, which merges
            # them.  Dedup those few byte-strings host-side by content.
            short_seen: set[str] = set()
            for i, line in enumerate(f):
                total += 1
                text = line.rstrip("\n")
                if len(text.encode("utf-8", "replace")) < cfg.shingle_k:
                    if text in short_seen:
                        continue
                    short_seen.add(text)
                backend.submit({"article": text, "url": f"L{i}"})
            backend.flush()
        print(f"kept {kept}/{total} docs (streamed)", file=sys.stderr)
        return 0

    from advanced_scrapper_tpu.pipeline.dedup import NearDupEngine

    cfg = _with_overrides(default_config().dedup, backend=args.backend)
    engine = NearDupEngine(cfg)
    with open(args.input, "r", encoding="utf-8", errors="replace") as f:
        docs = [line.rstrip("\n") for line in f]
    reps = engine.dedup_reps(docs)
    kept = 0
    with open_sink() as out:
        for i, r in enumerate(reps):
            if r == i:
                kept += 1
                out.write(docs[i] + "\n")
    print(f"kept {kept}/{len(docs)} docs", file=sys.stderr)
    return 0


def _import_pipeline(module: str, attr: str):
    import importlib

    try:
        mod = importlib.import_module(f"advanced_scrapper_tpu.pipeline.{module}")
    except ImportError as e:
        raise SystemExit(
            f"astpu: the '{module}' pipeline is not available in this build: {e}"
        ) from e
    return getattr(mod, attr)


def _with_overrides(cfg, **overrides):
    import dataclasses

    overrides = {k: v for k, v in overrides.items() if v is not None}
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def _cmd_harvest(args: argparse.Namespace) -> int:
    cfg = _with_overrides(default_config().harvest, transport=args.transport)
    if args.engine == "async":
        if args.transport is not None:
            # the async engine rides its own aiohttp session; silently
            # ignoring a requested browser transport would let the operator
            # believe it ran
            print("--engine async is plain-HTTP only; drop --transport "
                  "or use --engine threads")
            return 2
        run_harvest_async = _import_pipeline("harvest_async", "run_harvest_async")
        return run_harvest_async(cfg)
    run_harvest = _import_pipeline("harvest", "run_harvest")
    return run_harvest(cfg)


def _cmd_scrape(args: argparse.Namespace) -> int:
    run_scraper = _import_pipeline("scraper", "run_scraper")
    return run_scraper(_with_overrides(default_config().scraper, transport=args.transport))


def _cmd_enrich(args: argparse.Namespace) -> int:
    # --simple: the un-hardened single-pass flow (ref ticker_symbol_query.py)
    # — no retry ladder, no progress ledger, no cool-downs
    cfg = _with_overrides(
        default_config().enrich,
        hardened=False if getattr(args, "simple", False) else None,
    )
    if getattr(args, "crypto", False):
        run_crypto = _import_pipeline("enrich", "run_crypto_enrich")
        return run_crypto(cfg)
    run_enrich = _import_pipeline("enrich", "run_enrich")
    return run_enrich(cfg)


def _cmd_match(args: argparse.Namespace) -> int:
    run_matcher = _import_pipeline("matcher", "run_matcher")
    if args.refine and args.no_screen:
        print("astpu match: --refine requires the screen; drop --no-screen")
        return 2
    kw = {}
    if args.no_screen:
        kw["use_screen"] = False
    if args.refine:
        kw["use_refine"] = True
    elif args.no_refine:
        kw["use_refine"] = False
    # neither flag: run_matcher's "auto" default (dispatch the bound only
    # on batches whose survivor count clears the measured breakeven);
    # --refine/--no-refine conflicts are rejected by their argparse
    # mutually-exclusive group
    if getattr(args, "workers", None) is not None:
        kw["workers"] = args.workers
    try:
        return run_matcher(default_config().match, **kw)
    except ValueError as e:
        # e.g. --refine with the screen disabled via config/env
        print(f"astpu match: {e}")
        return 2


def _cmd_poll(args: argparse.Namespace) -> int:
    """Live topic poller + optional article drain (successor of the
    reference's experiental/04..10 infinite loops; bounded by --rounds)."""
    from advanced_scrapper_tpu.extractors import load_extractor
    from advanced_scrapper_tpu.net.transport import make_transport
    from advanced_scrapper_tpu.pipeline.poller import (
        DEFAULT_TOPIC_URL,
        drain_unscraped,
        poll_links,
    )
    from advanced_scrapper_tpu.storage.stores import ArticleStore, LinkStore

    import time as _time

    links = LinkStore(args.db)
    transport = make_transport(args.transport or default_config().scraper.transport)
    extractor = load_extractor(args.website) if args.drain else None
    articles = ArticleStore(args.db) if args.drain else None
    new = stored = rounds_done = 0
    try:
        # drain interleaves with polling (the reference's 09/10 pair runs
        # discovery and scraping concurrently forever) — a trailing-only
        # drain would never run under the default infinite rounds
        while args.rounds is None or rounds_done < args.rounds:
            new += poll_links(
                links,
                transport,
                topic_url=args.topic or DEFAULT_TOPIC_URL,
                interval=args.interval,
                max_iterations=1,
                mirror_csv=args.mirror_csv,
                scroll=args.scroll,
            )
            if args.drain:
                stored += drain_unscraped(
                    links,
                    articles,
                    transport,
                    extractor,
                    max_rounds=args.drain_rounds,
                )
            rounds_done += 1
            if args.rounds is None or rounds_done < args.rounds:
                _time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        transport.close()
    print(f"{new} new links → {args.db}")
    if args.drain:
        print(f"{stored} articles stored")
    return 0


def _cmd_serve(args: argparse.Namespace) -> int:
    """Lease server: resume-aware URL distribution + centralized parsing
    (successor of experiental/server1.py)."""
    import os

    from advanced_scrapper_tpu.extractors import load_extractor
    from advanced_scrapper_tpu.net.lease import LeaseServer
    from advanced_scrapper_tpu.storage.csvio import read_url_column, scraped_url_set

    cfg = default_config()
    scraper = cfg.scraper
    input_csv = args.input or scraper.input_csv
    if not os.path.exists(input_csv):
        print(f"Input CSV '{input_csv}' not found.")
        return 1
    success_csv = f"success_articles_{scraper.website}.csv"
    failed_csv = f"failed_articles_{scraper.website}.csv"
    urls = read_url_column(input_csv)
    scraped = scraped_url_set(success_csv, failed_csv)
    todo = [u for u in urls if u not in scraped]
    print(f"Serving {len(todo)} URLs ({len(urls) - len(todo)} already scraped)")
    feed = _with_overrides(cfg.feed, port=args.port)
    server = LeaseServer(feed, todo).start()
    print(f"Listening on {server.host}:{server.port} — Ctrl-C to stop")
    try:
        while not server.done():
            import time

            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    ok, bad = server.process_results(
        load_extractor(scraper.website), success_csv, failed_csv
    )
    print(f"Parsed results: {ok} success, {bad} failed")
    return 0


def _cmd_work(args: argparse.Namespace) -> int:
    """Lease worker node (successor of experiental/client1.py)."""
    from advanced_scrapper_tpu.net.lease import LeaseClient
    from advanced_scrapper_tpu.net.transport import make_transport

    cfg = default_config()
    feed = _with_overrides(cfg.feed, host=args.host, port=args.port)
    transport = args.transport or cfg.scraper.transport
    client = LeaseClient(feed, lambda: make_transport(transport))
    sent = client.run(max_seconds=args.max_seconds)
    print(f"Worker done: {sent} pages shipped")
    return 0


def _cmd_new_links(args: argparse.Namespace) -> int:
    from advanced_scrapper_tpu.utils.setops import new_links

    n = new_links(args.input, args.output, *args.done)
    print(f"{n} new links → {args.output}")
    return 0


def _cmd_split(args: argparse.Namespace) -> int:
    from advanced_scrapper_tpu.utils.setops import round_robin_split

    paths = round_robin_split(
        args.input, args.parts, *args.done, output_template=args.template
    )
    print("wrote " + ", ".join(paths))
    return 0


def _cmd_smoke(args: argparse.Namespace) -> int:
    """Environment sanity check (ref experiental/gdriver_test.py:1-13):
    device backend, native host kernels, transport, and one tiny dedup."""
    import numpy as np

    report: dict = {}
    ok = True
    try:
        import jax

        devs = jax.devices()
        report["jax"] = {
            "version": jax.__version__,
            "platform": devs[0].platform,
            "devices": len(devs),
        }
    except Exception as e:
        report["jax"] = {"error": str(e)}
        ok = False
    from advanced_scrapper_tpu.cpu.hostbatch import hostbatch_backend
    from advanced_scrapper_tpu.cpu.native import _load as _fm_load
    from advanced_scrapper_tpu.cpu import csvnative as _csv
    from advanced_scrapper_tpu.cpu import native as _fm

    _fm_load()
    _csv._load()
    report["native"] = {
        "fastmatch": _fm.BACKEND,
        "hostbatch": hostbatch_backend(),
        "csvscan": _csv.BACKEND,
    }
    try:
        from advanced_scrapper_tpu.net.transport import make_transport

        t = make_transport(args.transport, pages={"https://smoke/x": "<html></html>"})
        t.fetch("https://smoke/x") if args.transport == "mock" else None
        t.close()
        report["transport"] = {args.transport: "ok"}
    except Exception as e:
        report["transport"] = {args.transport: f"error: {e}"}
        ok = False
    try:
        from advanced_scrapper_tpu.pipeline.dedup import NearDupEngine

        reps = NearDupEngine().dedup_reps(["smoke test article body", "smoke test article body", "other"])
        assert reps.tolist()[1] == 0
        report["dedup"] = {"reps": np.asarray(reps).tolist()}
    except Exception as e:
        report["dedup"] = {"error": str(e)}
        ok = False
    report["ok"] = ok
    print(json.dumps(report, indent=2))
    return 0 if ok else 1


def _cmd_selftest(args: argparse.Namespace) -> int:
    """Integration ladder against REAL endpoints (ref experiental/
    02_test_1.py:45-69, 08_test.py:44-90 assert through the running stack
    against live URLs — the one class of bug mocks can't catch).

    Live rungs are double-gated (``--live`` AND ``ASTPU_LIVE=1``) because
    they send real traffic; without the gate only the offline rung runs.
    Each rung reports ``ok`` / ``skipped`` / ``unreachable`` — a dead
    network degrades to ``unreachable``, never a traceback; the exit code
    is 1 only when a rung REACHED its endpoint and misbehaved."""
    import os
    import tempfile

    from advanced_scrapper_tpu.net.transport import (
        FetchError,
        RequestsTransport,
        _resolve_binary,
    )

    report: dict = {}
    failed = False

    # rung 0 (always): the ladder harness itself over a mock — a live run
    # that fails rung 0 is a broken harness, not a broken endpoint
    try:
        from bs4 import BeautifulSoup

        from advanced_scrapper_tpu.extractors.template import extract_with_template

        soup = BeautifulSoup("<html><h1>t</h1></html>", "html.parser")
        data = extract_with_template(soup, {"title": "h1"})
        assert data["title"] == "t"
        report["harness"] = "ok"
    except Exception as e:
        report["harness"] = f"failed: {e}"
        failed = True

    live = bool(args.live) and os.environ.get("ASTPU_LIVE") == "1"
    if not live:
        why = (
            "pass --live and set ASTPU_LIVE=1"
            if not args.live
            else "ASTPU_LIVE=1 not set"
        )
        report["cdx"] = report["fetch"] = report["extract"] = f"skipped ({why})"
        report["ok"] = not failed
        print(json.dumps(report, indent=2))
        return 0 if not failed else 1

    # rung 1: one-shard CDX harvest over plain HTTP (ref
    # yahoo_links_selenium.py:31-34 — the L1 discovery path)
    try:
        from advanced_scrapper_tpu.config import HarvestConfig
        from advanced_scrapper_tpu.pipeline.harvest import (
            cdx_query_url,
            normalize_cdx_frame,
            parse_cdx_text,
        )

        cfg = HarvestConfig()
        t = RequestsTransport(timeout=30.0)
        try:
            text = t.fetch(cdx_query_url(args.prefix, cfg))
        finally:
            t.close()
        df = normalize_cdx_frame(parse_cdx_text(text))
        report["cdx"] = {"prefix": args.prefix, "rows": int(len(df))}
    except FetchError as e:
        report["cdx"] = f"unreachable ({e})"
    except Exception as e:
        report["cdx"] = f"failed: {e}"
        failed = True

    # rung 2: one real fetch through the first-party wire client, spawn
    # path included — only when a driver binary exists on this host
    driver = _resolve_binary("geckodriver") or _resolve_binary("chromedriver")
    if driver is None:
        report["fetch"] = "skipped (no geckodriver/chromedriver binary)"
    else:
        try:
            from advanced_scrapper_tpu.net.transport import (
                WireChromeTransport,
                WireFirefoxTransport,
            )

            from advanced_scrapper_tpu.net.webdriver import WebDriverError

            cls = (
                WireFirefoxTransport
                if "gecko" in os.path.basename(driver)
                else WireChromeTransport
            )
            t = cls(executable_path=driver)
            try:
                html = t.fetch(args.live_url)
            finally:
                t.close()
            report["fetch"] = {"driver": driver, "bytes": len(html)}
        except FetchError as e:
            report["fetch"] = f"unreachable ({e})"
        except WebDriverError as e:
            # a driver binary that won't spawn/start a session is a LOCAL
            # stack problem (e.g. driver without a browser): no endpoint
            # was reached, so per the exit contract this is not a failure
            report["fetch"] = f"skipped (driver: {e})"
        except Exception as e:
            report["fetch"] = f"failed: {e}"
            failed = True

    # rung 3: one control-plane extract (ref 02_test_1.py:45-69 — template
    # registered, then a live URL processed through the plane's pool)
    try:
        from advanced_scrapper_tpu.net.control import ControlPlane

        with tempfile.TemporaryDirectory() as d:
            plane = ControlPlane(
                lambda: RequestsTransport(timeout=30.0),
                templates_path=os.path.join(d, "templates.json"),
                workers=1,
                out_root=d,
            )
            try:
                plane.add_template("selftest", {"title": "title"})
                data = plane.extract(args.live_url, "selftest")
            finally:
                plane.shutdown()
        report["extract"] = {"title": data.get("title", "")[:80]}
    except FetchError as e:
        report["extract"] = f"unreachable ({e})"
    except Exception as e:
        report["extract"] = f"failed: {e}"
        failed = True

    report["ok"] = not failed
    print(json.dumps(report, indent=2))
    return 0 if not failed else 1


def _cmd_xdedup(args: argparse.Namespace) -> int:
    from advanced_scrapper_tpu.pipeline.cross_source import cross_source_dedup

    stats = cross_source_dedup(args.sources, args.output)
    print(json.dumps(stats, indent=2))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="astpu",
        description="TPU-native financial-news harvesting/dedup/matching framework",
    )
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("version", help="print version").set_defaults(fn=_cmd_version)
    sub.add_parser("config", help="print effective config").set_defaults(fn=_cmd_config)

    d = sub.add_parser("dedup", help="near-dup dedup of a line-delimited corpus")
    d.add_argument("input")
    d.add_argument("-o", "--output", default=None)
    d.add_argument(
        "--backend", default=None, choices=["scan", "oph", "pallas"],
        help="signature backend (default: config; scan is measured-fastest)",
    )
    d.add_argument(
        "--stream", action="store_true",
        help="bounded-memory streaming dedup (corpus never read whole; "
        "first-seen-wins across batches via the stream index)",
    )
    d.add_argument(
        "--index", default=None, choices=["exact", "bloom"],
        help="stream index: exact (attributed, grows with kept docs) or "
        "bloom (LSHBloom, fixed memory forever); --stream only",
    )
    d.set_defaults(fn=_cmd_dedup)

    h = sub.add_parser("harvest", help="CDX URL harvest -> deduped yfin_urls.csv")
    h.add_argument("--transport", default=None)
    h.add_argument(
        "--engine",
        choices=("threads", "async"),
        default="threads",
        help="threads: one transport per worker (browsers need this); "
        "async: one aiohttp session, semaphore-bounded (the Scrapy-slot "
        "engine — plain HTTP only)",
    )
    h.set_defaults(fn=_cmd_harvest)

    s = sub.add_parser("scrape", help="constant-rate article scrape")
    s.add_argument("--transport", default=None)
    s.set_defaults(fn=_cmd_scrape)

    e = sub.add_parser("enrich", help="Wikidata ticker/crypto enrichment")
    e.add_argument(
        "--crypto",
        action="store_true",
        help="enrich the crypto symbol list into info/crypto/ instead",
    )
    e.add_argument(
        "--simple",
        action="store_true",
        help="un-hardened single-pass queries (ref ticker_symbol_query.py; "
        "default is the rate-limit-protected flow)",
    )
    e.set_defaults(fn=_cmd_enrich)

    m = sub.add_parser("match", help="ticker→article entity matching")
    m.add_argument(
        "--no-screen", action="store_true",
        help="disable the TPU q-gram screen (pure reference scan)",
    )
    refine_group = m.add_mutually_exclusive_group()
    refine_group.add_argument(
        "--refine", action="store_true",
        help="force the device alignment-bound prune on every batch "
        "(default: auto — engages only past the measured breakeven pair "
        "count; see DESIGN.md §4)",
    )
    refine_group.add_argument(
        "--no-refine", action="store_true",
        help="never run the alignment bound (use on tunneled/high-latency "
        "device transports, where per-batch dispatch dominates)",
    )
    m.add_argument(
        "--workers", type=int, default=None,
        help="exact-verify process fan-out (0 = cpu_count, the reference's "
        "mp.Pool width; 1 = inline; default: config verify_workers)",
    )
    m.set_defaults(fn=_cmd_match)

    pl = sub.add_parser("poll", help="live topic poller → link store")
    pl.add_argument(
        "--db", default="crypto_news.db",
        help="sqlite path or postgres:// DSN (ref runs both stacks)",
    )
    pl.add_argument("--topic", default=None)
    pl.add_argument("--interval", type=float, default=3.0)
    pl.add_argument("--rounds", type=int, default=None, help="default: forever")
    pl.add_argument("--drain", action="store_true", help="also scrape unscraped links")
    pl.add_argument("--drain-rounds", type=int, default=1)
    pl.add_argument("--website", default="yfin")
    pl.add_argument("--transport", default=None)
    pl.add_argument(
        "--mirror-csv", default=None,
        help="also append new links to this CSV (ref 04_crypto_1.py:76-80)",
    )
    pl.add_argument(
        "--scroll", action="store_true",
        help="scroll-to-load discovery on scroll-capable transports (04:57-63)",
    )
    pl.set_defaults(fn=_cmd_poll)

    sv = sub.add_parser("serve", help="lease server: distribute URLs to workers")
    sv.add_argument("--input", default=None, help="URL csv (default scraper input)")
    sv.add_argument("--port", type=int, default=None)
    sv.set_defaults(fn=_cmd_serve)

    wk = sub.add_parser("work", help="lease client: fetch for a serve node")
    wk.add_argument("--host", default=None)
    wk.add_argument("--port", type=int, default=None)
    wk.add_argument("--transport", default=None)
    wk.add_argument("--max-seconds", type=float, default=3600.0)
    wk.set_defaults(fn=_cmd_work)

    nl = sub.add_parser("new-links", help="anti-join: urls not yet scraped")
    nl.add_argument("input")
    nl.add_argument("output")
    nl.add_argument("done", nargs="+", help="CSVs of already-scraped urls")
    nl.set_defaults(fn=_cmd_new_links)

    sp = sub.add_parser("split", help="round-robin shard split for N machines")
    sp.add_argument("input")
    sp.add_argument("-n", "--parts", type=int, required=True)
    sp.add_argument("--done", nargs="*", default=[])
    sp.add_argument("--template", default="part_{i}.csv")
    sp.set_defaults(fn=_cmd_split)

    xd = sub.add_parser(
        "xdedup", help="cross-source dedup over CSVs and sqlite stores"
    )
    xd.add_argument("sources", nargs="+")
    xd.add_argument("-o", "--output", default="xdedup_manifest.csv")
    xd.set_defaults(fn=_cmd_xdedup)

    st = sub.add_parser(
        "selftest",
        help="integration ladder; --live + ASTPU_LIVE=1 hits real endpoints",
    )
    st.add_argument("--live", action="store_true", help="enable network rungs")
    st.add_argument(
        "--prefix", default="aa", help="CDX shard prefix for the harvest rung"
    )
    st.add_argument(
        "--live-url",
        default="https://example.com/",
        help="URL for the fetch/extract rungs",
    )
    st.set_defaults(fn=_cmd_selftest)

    sm = sub.add_parser("smoke", help="environment sanity check (device, native, transport)")
    sm.add_argument("--transport", default="mock")
    sm.set_defaults(fn=_cmd_smoke)

    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
